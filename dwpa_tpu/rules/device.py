"""On-device hashcat rule mangling (the GPU-rule-engine equivalent).

hashcat runs its rule engine *on the accelerator*: the host uploads the
base wordlist once and every rule's mangling happens in the kernel, so
candidate bandwidth is multiplied by the rule count for free.  The
reference inherits that via ``hashcat -r`` (help_crack.py:773); our host
interpreter (rules/engine.py) is the behavioral spec, but host expansion
tops out around ~1M cand/s on a small host (BENCH_r03 host_feed) — it
cannot feed a mesh, and through the axon tunnel every expanded candidate
costs H2D bytes.  This module is the TPU seat of that GPU feature
(SURVEY §7.2 M5 "then on-device for mask/append families").

TPU-first design — rules are DATA, not code:

- A rule is encoded as an int32[S, 3] array of (opcode, arg1, arg2)
  steps.  One jitted interpreter — ``lax.scan`` over the steps, each
  step a ``lax.switch`` over the op table — serves EVERY rule at a
  given (batch, step-bucket) shape: compiling per rule (134 lines in a
  bestWPA-class set) would pay ~100 XLA compiles per work unit, while
  the data encoding pays a handful for a server's lifetime, exactly
  like the PBKDF2 salt-as-data design (ops/pbkdf2.py).
- Words are held as one uint8 lane per byte (uint8[B, W], W=64) so
  every op is an elementwise map or a gather along the unsharded byte
  axis; the dp-sharded batch axis is never communicated.  Unpack from
  and repack to the engine's packed uint32[B, 16] key blocks happen
  inside the same jit, so XLA fuses the whole expansion into the
  PBKDF2 feed.
- Semantics are bit-identical to rules/engine.py (differentially
  tested): same position conventions, same out-of-range no-ops, same
  reject filters (a rejected word's column is zeroed, the engine's
  oracle re-check keeps decode honest).  The single unsupported op is
  ``@`` (purge — data-dependent compaction, a poor fit for fixed-shape
  lanes); rules containing it fall back to host expansion.
- Length overflow: hashcat words may grow to 256 bytes mid-rule (host
  MAX_WORD); device lanes stop at W=64.  Growth is LENGTH-deterministic
  for every supported op (only ``@`` is content-dependent, and it is
  excluded), so the host pre-computes each rule's length trajectory
  over the batch's length vector (``simulate_lens`` — pure numpy) and
  routes the rare overflowing (word, rule) pairs to host expansion;
  the device independently flags them (ok=False) so its output stays
  correct even if a caller skips the simulation.
"""

import numpy as np

from .engine import _POS, MAX_WORD, Rule

#: Device lane width per word: intermediate rule results up to 64 bytes
#: stay on device; the final 8..63 PSK filter applies afterwards.  64
#: (not hashcat's 256-byte MAX_WORD) keeps the lane array at one uint8
#: per byte of key block — growth past it is length-deterministic, so
#: the host routes those rare (word, rule) pairs to its own interpreter
#: (see simulate_lens) instead of paying 4x the HBM traffic on every
#: batch for them.
W = 64

#: Final WPA PSK length bounds (models/m22000.py MIN/MAX_PSK_LEN).
_MIN_OUT, _MAX_OUT = 8, 63

# Op table order — _BRANCHES below and the encoder agree on these codes.
_OPS = [
    ":", "l", "u", "c", "C", "t", "T", "r", "d", "f", "{", "}", "[", "]",
    "D", "x", "O", "i", "o", "'", "$", "^", "s", "z", "Z", "q", "k", "K",
    "*", "L", "R", "+", "-", ".", ",", "y", "Y", "e", "E", "p",
    "<", ">", "_", "!", "/", "(", ")", "=", "%",
]
_OPCODE = {c: i for i, c in enumerate(_OPS)}

#: ops whose single arg is a position/count (0-9A-Z)
_POS1 = set("TD'zZLR+-.,yY<>_p")
#: ops whose single arg is a literal char
_CHR1 = set("$^!/()e")
#: (position, char) pairs
_POS_CHR = set("io=%")
#: (position, position) pairs
_POS_POS = set("xO*")
#: (char, char) pairs
_CHR_CHR = set("s")


def device_supported(rule: Rule) -> bool:
    """True when every step of ``rule`` runs on device (everything in
    the fast-kernel op set except ``@``)."""
    return all(op in _OPCODE for op, _ in rule.steps)


def encode_rule(rule: Rule) -> np.ndarray:
    """Rule -> int32[S, 3] (opcode, arg1, arg2) step array (device data)."""
    rows = []
    for op, args in rule.steps:
        a1 = a2 = 0
        if op in _POS1:
            a1 = _POS[args[0]]
        elif op in _CHR1:
            a1 = args.encode("latin1")[0]
        elif op == "E":
            a1 = 0x20  # title-case with the fixed space separator
        elif op in _POS_CHR:
            a1 = _POS[args[0]]
            a2 = args[1].encode("latin1")[0]
        elif op in _POS_POS:
            a1, a2 = _POS[args[0]], _POS[args[1]]
        elif op in _CHR_CHR:
            enc = args.encode("latin1")
            a1, a2 = enc[0], enc[1]
        rows.append((_OPCODE[op], a1, a2))
    if not rows:
        rows.append((0, 0, 0))  # ":" — empty rule is the noop
    return np.asarray(rows, dtype=np.int32)


def step_bucket(n: int) -> int:
    """Pad step counts to powers of two so the interpreter's jit cache
    hits across rules of nearby length (pad steps are ':' noops)."""
    b = 1
    while b < n:
        b *= 2
    return b


def simulate_lens(rule: Rule, lens: np.ndarray):
    """Length trajectory of ``rule`` over a batch's length vector.

    Returns ``(out_lens, hostneed)``: final lengths (int64) and a bool
    mask of columns whose INTERMEDIATE length ever exceeded the device
    lane width W — those (word, rule) pairs must be host-expanded (the
    host's 256-byte MAX_WORD allows shrink-back the device cannot
    represent).  Pure numpy; every supported op's length effect is
    content-independent, which is what makes this exact.
    """
    L = lens.astype(np.int64)
    hostneed = np.zeros(L.shape, dtype=bool)
    for op, args in rule.steps:
        if op in ("d", "f", "q"):
            L2 = 2 * L
        elif op == "p":
            L2 = (1 + _POS[args[0]]) * L
        elif op in ("z", "Z"):
            L2 = np.where(L > 0, L + _POS[args[0]], L)
        elif op in ("y", "Y"):
            n = _POS[args[0]]
            L2 = np.where(n <= L, L + n, L)
        elif op == "i":
            L2 = np.where(_POS[args[0]] <= L, L + 1, L)
        elif op == "x":
            p, m = _POS[args[0]], _POS[args[1]]
            L2 = np.where(p + m <= L, m, L)
        elif op == "O":
            p, m = _POS[args[0]], _POS[args[1]]
            L2 = np.where(p + m <= L, L - m, L)
        elif op == "D":
            L2 = np.where(_POS[args[0]] < L, L - 1, L)
        elif op in ("[", "]"):
            L2 = np.maximum(L - 1, 0)
        elif op == "'":
            L2 = np.minimum(L, _POS[args[0]])
        elif op in ("$", "^"):
            L2 = L + 1
        else:
            L2 = L
        hostneed |= L2 > W
        L = np.where(L2 > MAX_WORD, 0, L2)  # host rejects >256 outright
    return L, hostneed


# ---------------------------------------------------------------------------
# The interpreter (jax)
# ---------------------------------------------------------------------------


def _branches():
    """Build the op-branch table lazily (keeps jax out of module import)."""
    import jax.numpy as jnp

    iota = jnp.arange(W, dtype=jnp.int32)[None, :]  # [1, W]

    def c8(v):
        return jnp.asarray(v).astype(jnp.uint8)

    def isup(b):
        return (b >= 65) & (b <= 90)

    def islo(b):
        return (b >= 97) & (b <= 122)

    def tog(b):
        return jnp.where(islo(b), b - 32, jnp.where(isup(b), b + 32, b))

    def low(b):
        return jnp.where(isup(b), b + 32, b)

    def up(b):
        return jnp.where(islo(b), b - 32, b)

    def gather(b, idx):
        return jnp.take_along_axis(b, jnp.clip(idx, 0, W - 1), axis=1)

    def grow(b, L, ok, newL):
        """Apply a length increase; overflowing columns die (ok=False,
        len 0 — simulate_lens routes them to host expansion)."""
        over = newL > W
        return b, jnp.where(over, 0, newL), ok & ~over

    def condL(c, newB, newL, b, L):
        """Per-candidate conditional op: c bool[B]."""
        return (jnp.where(c[:, None], newB, b), jnp.where(c, newL, L))

    B_ = None  # branches close over shapes at trace time

    def noop(b, L, ok, a1, a2):
        return b, L, ok

    def f_l(b, L, ok, a1, a2):
        return low(b), L, ok

    def f_u(b, L, ok, a1, a2):
        return up(b), L, ok

    def f_c(b, L, ok, a1, a2):
        return jnp.where(iota == 0, up(b), low(b)), L, ok

    def f_C(b, L, ok, a1, a2):
        return jnp.where(iota == 0, low(b), up(b)), L, ok

    def f_t(b, L, ok, a1, a2):
        return tog(b), L, ok

    def f_T(b, L, ok, a1, a2):
        return jnp.where(iota == a1, tog(b), b), L, ok

    def f_r(b, L, ok, a1, a2):
        return gather(b, L[:, None] - 1 - iota), L, ok

    def f_d(b, L, ok, a1, a2):
        out = gather(b, jnp.where(iota < L[:, None], iota, iota - L[:, None]))
        return grow(out, L, ok, 2 * L)

    def f_f(b, L, ok, a1, a2):
        idx = jnp.where(iota < L[:, None], iota, 2 * L[:, None] - 1 - iota)
        return grow(gather(b, idx), L, ok, 2 * L)

    def f_rotl(b, L, ok, a1, a2):
        Ls = jnp.maximum(L, 1)[:, None]
        return gather(b, (iota + 1) % Ls), L, ok

    def f_rotr(b, L, ok, a1, a2):
        Ls = jnp.maximum(L, 1)[:, None]
        return gather(b, (iota + Ls - 1) % Ls), L, ok

    def f_delfirst(b, L, ok, a1, a2):
        return gather(b, iota + 1), jnp.maximum(L - 1, 0), ok

    def f_dellast(b, L, ok, a1, a2):
        return b, jnp.maximum(L - 1, 0), ok

    def f_D(b, L, ok, a1, a2):
        out = gather(b, jnp.where(iota < a1, iota, iota + 1))
        nb, nL = condL(a1 < L, out, L - 1, b, L)
        return nb, nL, ok

    def f_x(b, L, ok, a1, a2):
        out = gather(b, iota + a1)
        nb, nL = condL(a1 + a2 <= L, out, jnp.full_like(L, a2), b, L)
        return nb, nL, ok

    def f_O(b, L, ok, a1, a2):
        out = gather(b, jnp.where(iota < a1, iota, iota + a2))
        nb, nL = condL(a1 + a2 <= L, out, L - a2, b, L)
        return nb, nL, ok

    def f_i(b, L, ok, a1, a2):
        ins = jnp.where(iota == a1, c8(a2), gather(b, iota - 1))
        out = jnp.where(iota < a1, b, ins)
        c = a1 <= L
        over = (L + 1 > W) & c
        nb, nL = condL(c & ~over, out, L + 1, b, L)
        return nb, jnp.where(over, 0, nL), ok & ~over

    def f_o(b, L, ok, a1, a2):
        hit = (iota == a1) & (a1 < L[:, None])
        return jnp.where(hit, c8(a2), b), L, ok

    def f_trunc(b, L, ok, a1, a2):
        return b, jnp.minimum(L, a1), ok

    def f_append(b, L, ok, a1, a2):
        out = jnp.where(iota == L[:, None], c8(a1), b)
        return grow(out, L, ok, L + 1)

    def f_prepend(b, L, ok, a1, a2):
        out = jnp.where(iota == 0, c8(a1), gather(b, iota - 1))
        return grow(out, L, ok, L + 1)

    def f_sub(b, L, ok, a1, a2):
        hit = (b == c8(a1)) & (iota < L[:, None])
        return jnp.where(hit, c8(a2), b), L, ok

    def f_z(b, L, ok, a1, a2):
        out = gather(b, jnp.where(iota < a1, 0, iota - a1))
        c = L > 0
        newL = jnp.where(c, L + a1, L)
        over = newL > W
        nb, nL = condL(c & ~over, out, newL, b, L)
        return nb, jnp.where(over, 0, nL), ok & ~over

    def f_Z(b, L, ok, a1, a2):
        out = gather(b, jnp.minimum(iota, L[:, None] - 1))
        c = L > 0
        newL = jnp.where(c, L + a1, L)
        over = newL > W
        nb, nL = condL(c & ~over, out, newL, b, L)
        return nb, jnp.where(over, 0, nL), ok & ~over

    def f_q(b, L, ok, a1, a2):
        return grow(gather(b, iota // 2), L, ok, 2 * L)

    def f_k(b, L, ok, a1, a2):
        idx = jnp.where(iota == 0, 1, jnp.where(iota == 1, 0, iota))
        nb, nL = condL(L >= 2, gather(b, idx), L, b, L)
        return nb, nL, ok

    def f_K(b, L, ok, a1, a2):
        p, m = (L - 2)[:, None], (L - 1)[:, None]
        idx = jnp.where(iota == p, m, jnp.where(iota == m, p, iota))
        nb, nL = condL(L >= 2, gather(b, idx), L, b, L)
        return nb, nL, ok

    def f_swap(b, L, ok, a1, a2):
        idx = jnp.where(iota == a1, a2, jnp.where(iota == a2, a1, iota))
        nb, nL = condL((a1 < L) & (a2 < L), gather(b, idx), L, b, L)
        return nb, nL, ok

    def _at(b, L, a1, fn):
        hit = (iota == a1) & (a1 < L[:, None])
        return jnp.where(hit, fn(b), b)  # uint8 lanes wrap mod 256

    def f_shl(b, L, ok, a1, a2):
        return _at(b, L, a1, lambda x: x << 1), L, ok

    def f_shr(b, L, ok, a1, a2):
        return _at(b, L, a1, lambda x: x >> 1), L, ok

    def f_incr(b, L, ok, a1, a2):
        return _at(b, L, a1, lambda x: x + 1), L, ok

    def f_decr(b, L, ok, a1, a2):
        return _at(b, L, a1, lambda x: x + 255), L, ok

    def f_repl_next(b, L, ok, a1, a2):
        nxt = gather(b, iota + 1)
        hit = (iota == a1) & (a1 + 1 < L[:, None])
        return jnp.where(hit, nxt, b), L, ok

    def f_repl_prior(b, L, ok, a1, a2):
        prv = gather(b, iota - 1)
        hit = (iota == a1) & (a1 > 0) & (a1 < L[:, None])
        return jnp.where(hit, prv, b), L, ok

    def f_y(b, L, ok, a1, a2):
        out = gather(b, jnp.where(iota < a1, iota, iota - a1))
        c = a1 <= L
        newL = jnp.where(c, L + a1, L)
        over = newL > W
        nb, nL = condL(c & ~over, out, newL, b, L)
        return nb, jnp.where(over, 0, nL), ok & ~over

    def f_Y(b, L, ok, a1, a2):
        out = gather(b, jnp.where(iota < L[:, None], iota, iota - a1))
        c = a1 <= L
        newL = jnp.where(c, L + a1, L)
        over = newL > W
        nb, nL = condL(c & ~over, out, newL, b, L)
        return nb, jnp.where(over, 0, nL), ok & ~over

    def f_title(b, L, ok, a1, a2):
        lo = low(b)
        prev = gather(lo, iota - 1)
        upmask = (iota == 0) | (prev == c8(a1))
        return jnp.where(upmask & islo(lo), lo - 32, lo), L, ok

    def f_p(b, L, ok, a1, a2):
        Ls = jnp.maximum(L, 1)[:, None]
        return grow(gather(b, iota % Ls), L, ok, (1 + a1) * L)

    def f_rej_less(b, L, ok, a1, a2):
        return b, L, ok & (L < a1)

    def f_rej_greater(b, L, ok, a1, a2):
        return b, L, ok & (L > a1)

    def f_rej_eq(b, L, ok, a1, a2):
        return b, L, ok & (L == a1)

    def _contains(b, L, x):
        import jax.numpy as jnp

        return ((b == c8(x)) & (iota < L[:, None])).any(axis=1)

    def f_rej_contain(b, L, ok, a1, a2):
        return b, L, ok & ~_contains(b, L, a1)

    def f_rej_not_contain(b, L, ok, a1, a2):
        return b, L, ok & _contains(b, L, a1)

    def f_rej_first(b, L, ok, a1, a2):
        return b, L, ok & (L > 0) & (b[:, 0] == c8(a1))

    def f_rej_last(b, L, ok, a1, a2):
        last = jnp.take_along_axis(
            b, jnp.maximum(L - 1, 0)[:, None], axis=1
        )[:, 0]
        return b, L, ok & (L > 0) & (last == c8(a1))

    def f_rej_at(b, L, ok, a1, a2):
        at = jnp.take_along_axis(
            b, jnp.clip(jnp.full_like(L, a1), 0, W - 1)[:, None], axis=1
        )[:, 0]
        return b, L, ok & (a1 < L) & (at == c8(a2))

    def f_rej_count(b, L, ok, a1, a2):
        cnt = ((b == c8(a2)) & (iota < L[:, None])).sum(axis=1)
        return b, L, ok & (cnt >= a1)

    return [
        noop, f_l, f_u, f_c, f_C, f_t, f_T, f_r, f_d, f_f, f_rotl, f_rotr,
        f_delfirst, f_dellast, f_D, f_x, f_O, f_i, f_o, f_trunc, f_append,
        f_prepend, f_sub, f_z, f_Z, f_q, f_k, f_K, f_swap, f_shl, f_shr,
        f_incr, f_decr, f_repl_next, f_repl_prior, f_y, f_Y, f_title,
        f_title, f_p,
        f_rej_less, f_rej_greater, f_rej_eq, f_rej_contain,
        f_rej_not_contain, f_rej_first, f_rej_last, f_rej_at, f_rej_count,
    ]


_BRANCH_CACHE = []


def _get_branches():
    # Must be first called OUTSIDE any jit trace (expand_batch does so):
    # the branch closures capture a concrete iota constant, and building
    # them mid-trace would capture a tracer instead (leak on reuse).
    if not _BRANCH_CACHE:
        _BRANCH_CACHE.append(_branches())
    return _BRANCH_CACHE[0]


def expand_traced(packed, lens, steps):
    """Traceable core: one rule over one packed batch.

    ``(packed uint32[B,16], lens int32[B], steps int32[S,3]) ->
    uint32[B,16]`` with rejected/out-of-range columns zeroed.  Pure
    function of traced arrays — composable into larger jits: the
    engine's fused rules crack step (parallel/step.py build_rules_step)
    runs this under shard_map ahead of PBKDF2, because through the axon
    tunnel every separate jit dispatch costs ~0.1 s fixed and a
    per-rule expansion dispatch would throttle the whole attack.
    """
    import jax.numpy as jnp
    from jax import lax

    B = packed.shape[0]
    shifts = jnp.asarray([24, 16, 8, 0], dtype=jnp.uint32)
    b = ((packed[:, :, None] >> shifts[None, None, :])
         & jnp.uint32(0xFF)).astype(jnp.uint8).reshape(B, W)
    L = lens.astype(jnp.int32)
    ok = jnp.ones((B,), dtype=bool)
    branches = _get_branches()
    iota = jnp.arange(W, dtype=jnp.int32)[None, :]

    def body(carry, step):
        b, L, ok = carry
        b, L, ok = lax.switch(
            jnp.clip(step[0], 0, len(branches) - 1), branches,
            b, L, ok, step[1], step[2],
        )
        # invariant: byte lanes beyond the word length stay zero, so
        # gathers in later steps never leak stale bytes
        b = jnp.where(iota < L[:, None], b, 0)
        return (b, L, ok), None

    (b, L, ok), _ = lax.scan(body, (b, L, ok), steps)
    valid = ok & (L >= _MIN_OUT) & (L <= _MAX_OUT)
    out = (b.astype(jnp.uint32).reshape(B, 16, 4)
           << shifts[None, None, :]).sum(axis=2, dtype=jnp.uint32)
    return out * valid[:, None].astype(jnp.uint32)


def apply_rule_device(words, rule: Rule):
    """Differential-test helper: run one rule over host words on device.

    Returns a list aligned with ``words``: the mangled bytes where the
    device produced a valid candidate (8..63, not rejected), else None.
    The host interpreter (rule.apply + the PSK length filter) is the
    reference this must match exactly.
    """
    import jax

    from ..utils import bytesops as bo

    words = list(words)
    packed = bo.pack_passwords_be(words)
    lens = np.asarray([len(w) for w in words], np.int32)
    out = np.asarray(
        expand_batch(jax.device_put(packed), jax.device_put(lens),
                     encode_rule(rule))
    )
    out_lens, hostneed = simulate_lens(rule, lens)
    res = []
    for i in range(len(words)):
        if hostneed[i] or not out[i].any():
            res.append(None)
        else:
            res.append(bo.words_to_bytes_be(out[i])[: int(out_lens[i])])
    return res


_EXPAND_JITS = {}  # (impl, sharding or None) -> jitted expand


def stack_rules(steps_list, n_rules: int) -> np.ndarray:
    """Pad a chunk of encoded rules to one int32[n_rules, S, 3] stack.

    S = the chunk's max step bucket; missing steps and missing rules
    pad with ':' noops.  Fixing ``n_rules`` (the engine's RULES_CHUNK)
    keeps the fused step's jit signature constant across rulesets —
    a padded noop rule costs one wasted PBKDF2 pass on at most the
    final chunk, vs a fresh multi-second XLA compile per ruleset size.
    """
    S = step_bucket(max(s.shape[0] for s in steps_list))
    stack = np.zeros((n_rules, S, 3), dtype=np.int32)
    for r, s in enumerate(steps_list):
        stack[r, : s.shape[0]] = s
    return stack


def expand_batch(packed_dev, lens_dev, steps: np.ndarray, sharding=None):
    """Run one encoded rule over an uploaded base batch, on device.

    ``steps`` is padded to its power-of-two bucket with ':' noops so the
    jit cache is keyed by (B, bucket) only — a whole ruleset reuses one
    compilation.  Returns uint32[B, 16] packed candidates with rejected
    / out-of-range columns zeroed (a zero key block cannot decode to a
    valid PSK; the engine's oracle re-check makes false hits impossible
    to report).
    """
    import jax

    _get_branches()  # build the op table outside the jit trace
    fn = _EXPAND_JITS.get(("one", sharding))
    if fn is None:
        kw = {} if sharding is None else {"out_shardings": sharding}
        fn = jax.jit(expand_traced, **kw)
        _EXPAND_JITS[("one", sharding)] = fn
    S = step_bucket(steps.shape[0])
    if S != steps.shape[0]:
        pad = np.zeros((S - steps.shape[0], 3), dtype=np.int32)
        steps = np.concatenate([steps, pad])
    return fn(packed_dev, lens_dev, steps)
