"""Hashcat-compatible rule engine (host-side candidate mangling)."""

import os

from .engine import Rule, RuleError, apply_rules, parse_rule, parse_rules  # noqa: F401

#: the bundled WPA-tuned ruleset (the bestWPA.rule asset equivalent)
WPA_RULE_PATH = os.path.join(os.path.dirname(__file__), "wpa.rule")


def wpa_rules():
    """The bundled WPA ruleset, parsed (see wpa.rule for provenance)."""
    with open(WPA_RULE_PATH) as f:
        return parse_rules(f.read().splitlines())


def wpa_rules_text() -> str:
    """Raw text of the bundled ruleset (for dicts-table attachment)."""
    with open(WPA_RULE_PATH) as f:
        return f.read()
