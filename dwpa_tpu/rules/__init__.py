"""Hashcat-compatible rule engine (host-side candidate mangling)."""

from .engine import Rule, RuleError, apply_rules, parse_rule, parse_rules  # noqa: F401
