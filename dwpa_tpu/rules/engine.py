"""Hashcat rule-language interpreter (host side).

The reference distributes per-dictionary hashcat rule strings from the
server (stored in the dicts table, db/wpa.sql:48; merged and base64'd into
the work unit at web/content/get_work.php:84-92) and the client expands
wordlists with them (``hashcat --stdout -r``, help_crack/help_crack.py:508,
575).  This module interprets the rule language directly so the TPU client
needs no hashcat binary: rules expand candidates on the host, the device
sees only fixed-shape packed batches.

Covers every function family used by the reference's bestWPA.rule (noop,
case ops, toggles, reverse/rotate, append/prepend, truncate/delete,
insert/overwrite, substitute/purge, duplication) plus the rest of the
standard single-word function set, and the reject filters (``<``, ``>``,
``_``, ``!``, ``/``, ``(``, ``)``, ``=``, ``%``).  Memory/positional ops
that hashcat itself marks unsupported in fast-kernel mode are rejected at
parse time so bad server rules fail loudly, mirroring hashcat's behavior
of skipping invalid lines with a warning.

Semantics follow the public rule-language contract (word length cap 256;
positions encoded 0-9 then A-Z = 10..35; out-of-range positional ops leave
the word unchanged — hashcat "rule position exceeds word length" no-ops).
"""

from ..obs import get_logger

# child of the package logger: one setup_logging() config (obs/logs.py)
# covers the pool-guard warning below alongside every other emitter
_log = get_logger(__name__)

MAX_WORD = 256

# positions/counts: 0-9, A-Z (10..35)
_POS = {**{chr(48 + i): i for i in range(10)}, **{chr(65 + i): 10 + i for i in range(26)}}


class RuleError(ValueError):
    """Malformed or unsupported rule text."""


def _pos(ch: str) -> int:
    if ch not in _POS:
        raise RuleError(f"bad position char {ch!r}")
    return _POS[ch]


# op -> number of argument characters
_ARITY = {
    ":": 0, "l": 0, "u": 0, "c": 0, "C": 0, "t": 0, "r": 0, "d": 0, "f": 0,
    "{": 0, "}": 0, "[": 0, "]": 0, "q": 0, "k": 0, "K": 0, "E": 0,
    "T": 1, "p": 1, "D": 1, "'": 1, "z": 1, "Z": 1, "@": 1, "$": 1, "^": 1,
    "L": 1, "R": 1, "+": 1, "-": 1, ".": 1, ",": 1, "y": 1, "Y": 1, "e": 1,
    "s": 2, "x": 2, "O": 2, "i": 2, "o": 2, "*": 2,
    # reject filters
    "<": 1, ">": 1, "_": 1, "!": 1, "/": 1, "(": 1, ")": 1, "=": 2, "%": 2,
}


class Rule:
    """One parsed rule line: a sequence of (op, args) steps."""

    __slots__ = ("steps", "text")

    def __init__(self, steps, text):
        self.steps = steps
        self.text = text

    def __repr__(self):
        return f"Rule({self.text!r})"

    def apply(self, word: bytes):
        """Mangle ``word``; returns the new word or None (rejected)."""
        w = bytearray(word)
        for op, args in self.steps:
            w = _STEP[op](w, args)
            if w is None or len(w) > MAX_WORD:
                return None
        return bytes(w)


def parse_rule(text: str) -> Rule:
    """Parse one rule line (space-separated or contiguous functions)."""
    steps = []
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch in " \t":
            i += 1
            continue
        if ch not in _ARITY:
            raise RuleError(f"unsupported rule function {ch!r} in {text!r}")
        k = _ARITY[ch]
        args = text[i + 1 : i + 1 + k]
        if len(args) != k:
            raise RuleError(f"truncated args for {ch!r} in {text!r}")
        steps.append((ch, args))
        i += 1 + k
    return Rule(steps, text)


def parse_rules(lines, on_error: str = "skip"):
    """Parse many rule lines; '#' comments and blanks ignored.

    ``on_error``: "skip" drops bad lines (hashcat's behavior), "raise"
    propagates RuleError.
    """
    out = []
    for line in lines:
        if isinstance(line, bytes):
            line = line.decode("utf-8", "replace")
        line = line.rstrip("\r\n")
        if not line.strip() or line.lstrip().startswith("#"):
            continue
        try:
            out.append(parse_rule(line))
        except RuleError:
            if on_error == "raise":
                raise
    return out


def apply_rules(rules, words, workers: int = 0, force_pool: bool = False):
    """Expand: yield every (rule, word) mangling, skipping rejects.

    Order matches hashcat --stdout: for each word, each rule in file
    order — with or without ``workers``, so resume skip-by-count and
    differential tests see one canonical stream.

    ``workers > 1`` fans the expansion over a process pool in
    order-preserving chunks: single-process expansion sustains ~0.8M
    cand/s, enough to feed one v5e chip (~230k PMK/s) but not a mesh
    (SURVEY §7.3.3 "keeping the device fed"); the pool scales the host
    side roughly linearly until packing/H2D dominates — PROVIDED the
    host has cores to spare.  On a host with fewer than ``workers + 1``
    cores the pool contends with the feeding process and measures
    *slower* than serial (2-core container: 769k pooled vs 995k serial,
    BENCH_r03 host_feed), so ``--rule-workers`` is auto-ignored there
    with a warning; ``force_pool`` overrides the guard (benchmarks use
    it to keep tracking the true pooled rate).
    """
    if workers and workers > 1:
        ncpu = _usable_cpus()
        if force_pool or ncpu >= workers + 1:
            yield from _apply_rules_pooled(rules, words, workers)
            return
        if workers not in _POOL_GUARD_WARNED:
            # once per (process, worker count): the condition can't
            # change at runtime and a client hits this per dict stream
            _POOL_GUARD_WARNED.add(workers)
            _log.warning(
                "rule-expansion pool disabled: %d workers need %d cores, host "
                "has %d (pooled expansion measures slower than serial when "
                "the pool contends with the feed process)",
                workers, workers + 1, ncpu,
            )
    for word in words:
        for rule in rules:
            w = rule.apply(word)
            if w is not None:
                yield w


_WORKER_RULES = {}  # worker-side: rules-key -> parsed [Rule]
_POOLS = {}         # parent-side: worker count -> live Pool (reused)
_POOL_GUARD_WARNED = set()  # worker counts already warned about


def _usable_cpus() -> int:
    """CPUs this process may actually run on — sched_getaffinity sees
    cgroup/cpuset pins that os.cpu_count() (whole-machine) does not,
    and a 2-core-pinned container on a 64-core host is exactly where
    the pool guard must trip."""
    import os

    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):  # non-Linux
        return os.cpu_count() or 1


def _pool_expand(args):
    texts, chunk = args
    # texts ride along with every chunk (~1 KB) so the pool can be
    # reused across different rule sets; each worker parses a given set
    # once and caches it, keyed by the texts tuple itself (a hash() key
    # could collide across rulesets and silently mangle candidates).
    rules = _WORKER_RULES.get(texts)
    if rules is None:
        rules = _WORKER_RULES.setdefault(texts, [parse_rule(t) for t in texts])
    out = []
    for word in chunk:
        for rule in rules:
            w = rule.apply(word)
            if w is not None:
                out.append(w)
    return out


def _get_pool(workers: int):
    """One long-lived pool per worker count, shared by every
    apply_rules call in the process — a work unit streams up to ~17
    dictionaries and must not pay interpreter spawn for each."""
    pool = _POOLS.get(workers)
    if pool is None:
        import atexit
        import multiprocessing

        # spawn, not fork: the calling client runs with jax's thread
        # pools live, and forking a threaded process can deadlock.
        # Spawn imposes the standard multiprocessing contract — the
        # caller's __main__ must be import-safe (true for ``python -m
        # dwpa_tpu.client`` and the guarded zipapp stub).
        ctx = multiprocessing.get_context("spawn")
        pool = ctx.Pool(workers)
        _POOLS[workers] = pool
        atexit.register(pool.terminate)
    return pool


def _apply_rules_pooled(rules, words, workers, chunk_words: int = 2048):
    import collections
    import itertools

    texts = tuple(r.text for r in rules)
    it = iter(words)
    chunks = iter(lambda: list(itertools.islice(it, chunk_words)), [])
    pool = _get_pool(workers)
    # Bounded in-flight window: submit at most workers+2 chunks ahead of
    # the consumer, so a slow downstream (the device feed) applies
    # backpressure instead of the expanded keyspace piling up in RAM
    # (imap's result cache is unbounded).
    pending = collections.deque()
    for chunk in chunks:
        pending.append(pool.apply_async(_pool_expand, ((texts, chunk),)))
        if len(pending) > workers + 2:
            yield from pending.popleft().get()
    while pending:
        yield from pending.popleft().get()


# ---------------------------------------------------------------------------
# Step implementations.  Each takes (bytearray, argstring) -> bytearray|None.
# ---------------------------------------------------------------------------


def _tog(b: int) -> int:
    if 97 <= b <= 122:
        return b - 32
    if 65 <= b <= 90:
        return b + 32
    return b


def _noop(w, a):
    return w


def _lower(w, a):
    return bytearray(bytes(w).lower())


def _upper(w, a):
    return bytearray(bytes(w).upper())


def _capitalize(w, a):
    return bytearray(bytes(w[:1]).upper() + bytes(w[1:]).lower())


def _inv_capitalize(w, a):
    return bytearray(bytes(w[:1]).lower() + bytes(w[1:]).upper())


def _toggle_all(w, a):
    return bytearray(_tog(b) for b in w)


def _toggle_at(w, a):
    p = _pos(a[0])
    if p < len(w):
        w[p] = _tog(w[p])
    return w


def _reverse(w, a):
    w.reverse()
    return w


def _duplicate(w, a):
    return w + w


def _repeat_n(w, a):
    return w * (_pos(a[0]) + 1)


def _reflect(w, a):
    return w + bytearray(reversed(w))


def _rotl(w, a):
    return w[1:] + w[:1]


def _rotr(w, a):
    return w[-1:] + w[:-1]


def _del_first(w, a):
    return w[1:]


def _del_last(w, a):
    return w[:-1]


def _del_at(w, a):
    p = _pos(a[0])
    if p < len(w):
        del w[p]
    return w


def _extract(w, a):
    p, m = _pos(a[0]), _pos(a[1])
    if p + m > len(w):
        return w
    return w[p : p + m]


def _omit(w, a):
    p, m = _pos(a[0]), _pos(a[1])
    if p + m > len(w):
        return w
    return w[:p] + w[p + m :]


def _insert(w, a):
    p = _pos(a[0])
    if p > len(w):
        return w
    return w[:p] + bytearray(a[1].encode("latin1")) + w[p:]


def _overwrite(w, a):
    p = _pos(a[0])
    if p < len(w):
        w[p] = a[1].encode("latin1")[0]
    return w


def _truncate_at(w, a):
    return w[: _pos(a[0])]


def _append(w, a):
    return w + bytearray(a.encode("latin1"))


def _prepend(w, a):
    return bytearray(a.encode("latin1")) + w


def _substitute(w, a):
    x, y = a[0].encode("latin1")[0], a[1].encode("latin1")[0]
    return bytearray(y if b == x else b for b in w)


def _purge(w, a):
    x = a.encode("latin1")[0]
    return bytearray(b for b in w if b != x)


def _dup_first(w, a):
    return w[:1] * _pos(a[0]) + w


def _dup_last(w, a):
    return w + w[-1:] * _pos(a[0])


def _dup_all(w, a):
    out = bytearray()
    for b in w:
        out += bytes((b, b))
    return out


def _swap_front(w, a):
    if len(w) >= 2:
        w[0], w[1] = w[1], w[0]
    return w


def _swap_back(w, a):
    if len(w) >= 2:
        w[-1], w[-2] = w[-2], w[-1]
    return w


def _swap_at(w, a):
    p, m = _pos(a[0]), _pos(a[1])
    if p < len(w) and m < len(w):
        w[p], w[m] = w[m], w[p]
    return w


def _shift_left(w, a):
    p = _pos(a[0])
    if p < len(w):
        w[p] = (w[p] << 1) & 0xFF
    return w


def _shift_right(w, a):
    p = _pos(a[0])
    if p < len(w):
        w[p] >>= 1
    return w


def _incr(w, a):
    p = _pos(a[0])
    if p < len(w):
        w[p] = (w[p] + 1) & 0xFF
    return w


def _decr(w, a):
    p = _pos(a[0])
    if p < len(w):
        w[p] = (w[p] - 1) & 0xFF
    return w


def _replace_next(w, a):
    p = _pos(a[0])
    if p + 1 < len(w):
        w[p] = w[p + 1]
    return w


def _replace_prior(w, a):
    p = _pos(a[0])
    if 0 < p < len(w):
        w[p] = w[p - 1]
    return w


def _dup_block_front(w, a):
    p = _pos(a[0])
    if p > len(w):
        return w
    return w[:p] + w


def _dup_block_back(w, a):
    p = _pos(a[0])
    if p > len(w):
        return w
    return w + w[len(w) - p :]


def _title(w, a):
    sep = a.encode("latin1")[0] if a else 0x20
    out = bytearray(bytes(w).lower())
    up = True
    for i, b in enumerate(out):
        if up:
            out[i] = _tog(b) if 97 <= b <= 122 else b
        up = b == sep
    return out


def _rej_less(w, a):
    return w if len(w) < _pos(a[0]) else None


def _rej_greater(w, a):
    return w if len(w) > _pos(a[0]) else None


def _rej_len_eq(w, a):
    return w if len(w) == _pos(a[0]) else None


def _rej_contain(w, a):
    return None if a.encode("latin1")[0] in w else w


def _rej_not_contain(w, a):
    return w if a.encode("latin1")[0] in w else None


def _rej_first(w, a):
    return w if w[:1] == a.encode("latin1") else None


def _rej_last(w, a):
    return w if w[-1:] == a.encode("latin1") else None


def _rej_at(w, a):
    p = _pos(a[0])
    return w if p < len(w) and w[p] == a[1].encode("latin1")[0] else None


def _rej_count(w, a):
    n, x = _pos(a[0]), a[1].encode("latin1")[0]
    return w if bytes(w).count(bytes((x,))) >= n else None


_STEP = {
    ":": _noop, "l": _lower, "u": _upper, "c": _capitalize, "C": _inv_capitalize,
    "t": _toggle_all, "T": _toggle_at, "r": _reverse, "d": _duplicate,
    "p": _repeat_n, "f": _reflect, "{": _rotl, "}": _rotr, "[": _del_first,
    "]": _del_last, "D": _del_at, "x": _extract, "O": _omit, "i": _insert,
    "o": _overwrite, "'": _truncate_at, "$": _append, "^": _prepend,
    "s": _substitute, "@": _purge, "z": _dup_first, "Z": _dup_last,
    "q": _dup_all, "k": _swap_front, "K": _swap_back, "*": _swap_at,
    "L": _shift_left, "R": _shift_right, "+": _incr, "-": _decr,
    ".": _replace_next, ",": _replace_prior, "y": _dup_block_front,
    "Y": _dup_block_back, "e": _title, "E": lambda w, a: _title(w, " "),
    "<": _rej_less, ">": _rej_greater, "=": _rej_at, "_": _rej_len_eq,
    "!": _rej_contain, "/": _rej_not_contain, "(": _rej_first, ")": _rej_last,
    "%": _rej_count,
}
