"""Double-buffered device staging over a framed candidate-block stream.

``M22000Engine._prepare``'s docstring has always hinted that its async
``device_put`` overlaps the previous batch's compute; ``DeviceStager``
formalizes that overlap as a component: it pulls block N+1 from the
feed and enqueues its H2D (``engine._prepare_block`` →
``shard_candidates``, an async transfer) BEFORE handing block N to the
caller for dispatch — so at every yield the next block's candidate
upload is already in flight behind the current block's device steps.

The stager composes with the engine's ``_Pipeline`` (which trails the
hits-gate sync ``PIPELINE_DEPTH`` batches behind dispatch): the
pipeline hides the device->host gate latency, the stager hides the
host->device candidate upload, and the feed's producer threads hide
the packing — the three layers of the input pipeline every
training/inference stack grows, here for candidates instead of
examples.

Stream-order and lockstep contracts are untouched: blocks are staged
and yielded strictly in feed order, and a block is staged exactly once
(a multi-process mesh sees the same ``shard_candidates`` sequence it
would without the stager, just earlier).
"""

from collections import deque


class DeviceStager:
    """Yield ``(block, prep)`` with ``depth`` blocks' H2D staged ahead.

    ``depth=1`` is classic double buffering: one staged block in flight
    beyond the one being dispatched.  ``prep`` is the engine's prepared
    triple (or None for a single-process block with no valid words —
    the caller skips it but still reports its ``count``).
    """

    def __init__(self, engine, blocks, depth: int = 1):
        self.engine = engine
        self.blocks = iter(blocks)
        self.depth = max(0, int(depth))

    def __iter__(self):
        staged = deque()  # (block, prep), oldest first
        exhausted = False
        while True:
            while not exhausted and len(staged) <= self.depth:
                blk = next(self.blocks, None)
                if blk is None:
                    exhausted = True
                    break
                # async H2D starts here, ahead of the caller's dispatch
                staged.append((blk, self.engine._prepare_block(blk)))
            if not staged:
                return
            yield staged.popleft()
