"""Persistent packed-dictionary cache: the O(1)-seek warm dict feed.

The workload model (PAPER.md) re-cracks every new ESSID group against
the same server-published dict set, keyed by ``dicts.dhash`` — so the
host-side cost of a dict pass (gunzip + ``$HEX`` decode + native
packing, ``gen.DictStream`` + ``pack_candidates_fast``) is paid on
effectively 100%-recurring inputs, and ``DictStream(skip=N)`` replays
the whole gzip prefix to honor a resume.  This module caches the
RESULT of that work per dict, in the design language of ``pmkstore/``:
CRC-framed chunks in a per-dict segment file, torn-tail tolerance on
open, whole-file LRU eviction under a byte cap.

On-disk format (one file per dict, ``<root>/<dhash>.dcache``):

- 8-byte magic ``b"DWDCCH1\\n"`` + the dict's raw 16-byte md5 (dhash) —
  a cache file copied or renamed under another dict's key is detected
  and treated as a miss (dhash-mismatch invalidation);
- CRC-framed chunks: ``b"DCTF" | payload_len u32 LE | crc32 u32 LE |
  payload`` where the payload is ``word_offset u64 | nwords u32 |
  nvalid u32 | lens uint8[nwords] | pad-to-4 | rows u32 LE
  [nvalid * 16]``.  ``lens[i]`` is the DECODED length of word
  ``offset + i`` when it passes the 8..63 PSK filter, else 0, so a
  chunk self-describes both the word count the stream framing sees and
  the packed-row subset the engine stages — any ``(batch_size, nproc,
  pid, skip)`` geometry can be served from one cache by column
  slicing, and a ``(offset, count)`` seek is a bisect on the chunk
  index, never a prefix replay;
- a final ``b"DCTE"`` END frame (``total_words u64 | total_valid
  u64``) seals the file.  The load walk verifies every frame's CRC and
  the offset chain; a torn tail, a corrupt frame, or a missing END
  makes the whole entry a MISS — the feed falls back to cold
  streaming, so a damaged cache can slow a pass but never corrupt the
  word stream;
- writes go to ``<final>.tmp-<pid>`` and ``os.replace`` into place on
  commit, so concurrent writers and crashes leave either the old entry
  or a complete new one.

The writer additionally cross-checks the native packer's output
against a Python model of the decode/filter (``_valid_len``): any
disagreement abandons the cache write and the pass stays cold —
never-wrong-words is enforced at write time too, not just by CRC.

Producer-thread discipline (lint rule DW111, mirroring DW107/DW108):
cache I/O — ``reader``/``writer``/``add_many``/``commit``/``chunks`` —
belongs to the feed's producer side (``dwpa_tpu/feed/``); consumer-side
engine code receives pre-packed blocks and never opens cache segments.
Everything here is pure host work — no jax imports, by design.

Metrics (README "Dict cache"): ``dwpa_dictcache_hit_blocks_total`` /
``dwpa_dictcache_miss_blocks_total`` counters,
``dwpa_dictcache_bytes`` and ``dwpa_dictcache_words_per_s`` (labeled
``feed="warm"|"cold"``) gauges.
"""

import bisect
import mmap
import os
import re
import struct
import zlib

import numpy as np

from ..utils.fsio import fsync_dir

MAGIC = b"DWDCCH1\n"
#: the rules-base species (``<dhash>.rbase``): same framing, but the
#: payload memoizes the DEVICE RULE-EXPANSION split — raw base lengths
#: (no ``$HEX`` decode, no 8..63 filter: rules can shrink/grow any
#: base) with ``0xFF`` marking host-fallback words, packed rows for the
#: eligible bases only, and the fallback words verbatim — so a warm
#: rules unit skips both the split and the pack (``feed.framing
#: .RulesPrep`` / ``M22000Engine._rules_flush``)
RBASE_MAGIC = b"DWRBCH1\n"
FRAME_MAGIC = b"DCTF"
END_MAGIC = b"DCTE"
FRAME_HEADER = len(FRAME_MAGIC) + 8   # magic + payload_len u32 + crc32 u32
HEADER = len(MAGIC) + 16              # file magic + raw dhash

#: words per cached chunk — the seek granularity (a ``(offset, count)``
#: lookup scans at most one chunk's lens column) and the writer's
#: packing batch; 4096 words is <= ~260 KiB of rows per frame
CHUNK_WORDS = 4096

#: the WPA-PSK length filter the packer applies (m22000.py
#: MIN_PSK_LEN/MAX_PSK_LEN — duplicated as protocol constants so this
#: host-only module never imports the jax-importing engine)
_MIN_LEN, _MAX_LEN = 8, 63

_DHASH_RE = re.compile(r"^[0-9a-f]{32}$")
_XDIGITS = frozenset(b"0123456789abcdefABCDEF")


def _valid_len(w: bytes) -> int:
    """Decoded length of ``w`` if it passes the PSK filter, else 0 —
    the Python model of ``native.pack_candidates_fast``'s per-word
    decision (pack_fast.cpp ``try_unhex`` + length filter), used to
    build the lens column and cross-check the native packer."""
    n = len(w)
    if 7 <= n <= 134 and w.startswith(b"$HEX[") and w.endswith(b"]"):
        k = n - 6
        if k % 2 == 0 and k // 2 <= 64 and all(c in _XDIGITS for c in w[5:-1]):
            n = k // 2
    return n if _MIN_LEN <= n <= _MAX_LEN else 0


class CachedDict:
    """One complete, mmap-backed packed dict — the warm read side.

    Chunk views are zero-copy ``np.frombuffer`` windows into the mmap;
    the mapping stays alive as long as any view does (numpy holds the
    buffer), so dropping a CachedDict mid-serve is safe and ``close``
    is only for tests that need the unmap to happen eagerly.
    """

    __slots__ = ("_mm", "_base", "_nwords", "_nvalid", "_lens_off",
                 "_rows_off", "total_words", "total_valid", "nbytes")

    def __init__(self, mm, base, nwords, nvalid, lens_off, rows_off,
                 total_words, total_valid):
        self._mm = mm
        self._base = base
        self._nwords = nwords
        self._nvalid = nvalid
        self._lens_off = lens_off
        self._rows_off = rows_off
        self.total_words = total_words
        self.total_valid = total_valid
        self.nbytes = len(mm)

    @classmethod
    def _load(cls, mm, dhash: str):
        """Frame-walk a cache file; None on ANY structural doubt (bad
        magic, dhash mismatch, bad CRC, broken offset chain, missing
        END) — the caller then treats the dict as cold."""
        if len(mm) < HEADER or mm[:len(MAGIC)] != MAGIC:
            return None
        if mm[len(MAGIC):HEADER] != bytes.fromhex(dhash):
            return None
        buf = memoryview(mm)
        pos, off_expect, valid_total = HEADER, 0, 0
        base, nwords, nvalid, lens_off, rows_off = [], [], [], [], []
        totals = None
        while pos + FRAME_HEADER <= len(mm):
            magic = bytes(buf[pos:pos + 4])
            plen, crc = struct.unpack_from("<II", buf, pos + 4)
            start, end = pos + FRAME_HEADER, pos + FRAME_HEADER + plen
            if magic not in (FRAME_MAGIC, END_MAGIC) or end > len(mm):
                break
            if zlib.crc32(buf[start:end]) & 0xFFFFFFFF != crc:
                break
            if magic == END_MAGIC:
                if plen == 16:
                    totals = struct.unpack_from("<QQ", buf, start)
                break
            if plen < 16:
                break
            o, nw, nv = struct.unpack_from("<QII", buf, start)
            if o != off_expect or plen != 16 + nw + (-nw % 4) + 64 * nv:
                break
            base.append(o)
            nwords.append(nw)
            nvalid.append(nv)
            lens_off.append(start + 16)
            rows_off.append(start + 16 + nw + (-nw % 4))
            off_expect = o + nw
            valid_total += nv
            pos = end
        if totals is None or totals != (off_expect, valid_total):
            return None
        return cls(mm, base, nwords, nvalid, lens_off, rows_off,
                   off_expect, valid_total)

    def chunks(self, start: int = 0):
        """Yield ``(chunk_word_offset, lens uint8[nwords],
        rows u32[nvalid, 16])`` zero-copy views from the chunk
        containing word index ``start`` onward — the O(1) seek: a
        bisect on the chunk index, no prefix replay."""
        i = max(0, bisect.bisect_right(self._base, start) - 1)
        for k in range(i, len(self._base)):
            nw, nv = self._nwords[k], self._nvalid[k]
            lens = np.frombuffer(self._mm, np.uint8, nw, self._lens_off[k])
            rows = np.frombuffer(self._mm, "<u4", nv * 16,
                                 self._rows_off[k]).reshape(nv, 16)
            yield self._base[k], lens, rows

    def close(self):
        """Eager unmap (tests only — raises BufferError while chunk
        views are still alive; production drops the reference and lets
        the views keep the mapping)."""
        if self._mm is not None:
            self._mm.close()
            self._mm = None


class DictCacheWriter:
    """Append-side of one dict's cache entry, fed by the cold tee.

    NEVER raises out of ``add_many``/``commit``/``abort``: a cache
    write failure (disk full, packer disagreement, native packer gone)
    only disables caching for this dict — the word stream the consumer
    sees is untouched.  Chunks are packed with the SAME native packer
    the cold path uses and cross-checked against ``_valid_len``; any
    mismatch abandons the entry.
    """

    #: file magic — the rules-base subclass swaps in its own species
    _MAGIC = MAGIC

    def __init__(self, cache, dhash: str, final_path: str):
        self._cache = cache
        self._final = final_path
        self._tmp = f"{final_path}.tmp-{os.getpid()}"
        self._buf = []
        self._off = 0        # words flushed so far
        self._nvalid = 0
        self.failed = False
        self.committed = False
        self._f = open(self._tmp, "wb")
        self._f.write(self._MAGIC + bytes.fromhex(dhash))

    def add_many(self, words):
        """Buffer a batch of post-DictStream words (order = stream
        order); full chunks are packed and framed out immediately."""
        if self.failed or self.committed:
            return
        try:
            self._buf.extend(words)
            while len(self._buf) >= CHUNK_WORDS:
                self._flush(self._buf[:CHUNK_WORDS])
                del self._buf[:CHUNK_WORDS]
        except Exception:
            self._fail()

    def _flush(self, words):
        from ..native import pack_candidates_fast

        lens = np.fromiter((_valid_len(w) for w in words), np.uint8,
                           count=len(words))
        fast = pack_candidates_fast(words, _MIN_LEN, _MAX_LEN,
                                    capacity=len(words))
        if fast is None:
            raise RuntimeError("native packer unavailable")
        rows, plens, nvalid = fast
        # cross-check: the cache must reproduce the cold path EXACTLY,
        # or it must not exist
        if (nvalid != int(np.count_nonzero(lens))
                or not np.array_equal(np.asarray(plens[:nvalid], np.uint8),
                                      lens[lens > 0])):
            raise RuntimeError("packer/lens-model disagreement")
        payload = (struct.pack("<QII", self._off, len(words), nvalid)
                   + lens.tobytes() + b"\x00" * (-len(words) % 4)
                   + rows[:nvalid].astype("<u4", copy=False).tobytes())
        self._frame(FRAME_MAGIC, payload)
        self._off += len(words)
        self._nvalid += nvalid

    def _frame(self, magic, payload):
        self._f.write(magic + struct.pack(
            "<II", len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload)

    def commit(self) -> bool:
        """Seal (END frame), fsync, and atomically publish the entry;
        returns False if the write failed anywhere along the way.

        The directory is fsynced after the replace so the publish
        itself survives power loss — without it the rename can vanish
        and leave the fsynced data orphaned under the tmp name.  (For a
        cache that only costs a re-stream, but the END-frame contract
        promises "either absent or complete", so the commit path keeps
        the full durable-rename idiom — see the fsync audit notes in
        ``utils.fsio``.)"""
        if self.failed or self.committed:
            return self.committed
        try:
            if self._buf:
                self._flush(self._buf)
                self._buf = []
            self._frame(END_MAGIC, struct.pack("<QQ", self._off, self._nvalid))
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            self._f = None
            os.replace(self._tmp, self._final)
            fsync_dir(os.path.dirname(os.path.abspath(self._final)))
            self.committed = True
            self._cache._committed()
            return True
        except Exception:
            self._fail()
            return False

    def abort(self):
        """Drop the partial entry (idempotent; no-op after commit)."""
        if self.committed:
            return
        self.failed = True
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
        try:
            os.unlink(self._tmp)
        except OSError:
            pass

    _fail = abort


class CachedRulesBase:
    """One complete, mmap-backed rules-base entry — the warm read side
    of the device rule-expansion feed (``RBASE_MAGIC`` species).

    Chunk payload: ``word_offset u64 | nwords u32 | nplain u32 | marks
    uint8[nwords] | pad-to-4 | rows u32 LE [nplain * 16] | fallback
    blob`` where ``marks[i]`` is the raw base length of word ``offset +
    i`` (eligible for device expansion) or ``0xFF`` (host-fallback
    word: > 63 bytes or a ``HEX[`` carrier), rows pack the eligible
    bases only, and the blob is ``len u32 LE | bytes`` per fallback
    word in stream order, zero-padded to 4.  END totals are
    ``(total_words, total_plain)``.
    """

    __slots__ = ("_mm", "_base", "_nwords", "_nplain", "_marks_off",
                 "_rows_off", "_fb_off", "_fb_end", "total_words",
                 "total_plain", "nbytes")

    def __init__(self, mm, base, nwords, nplain, marks_off, rows_off,
                 fb_off, fb_end, total_words, total_plain):
        self._mm = mm
        self._base = base
        self._nwords = nwords
        self._nplain = nplain
        self._marks_off = marks_off
        self._rows_off = rows_off
        self._fb_off = fb_off
        self._fb_end = fb_end
        self.total_words = total_words
        self.total_plain = total_plain
        self.nbytes = len(mm)

    @classmethod
    def _load(cls, mm, dhash: str):
        """Frame-walk; None on ANY structural doubt (miss semantics of
        ``CachedDict._load``)."""
        if len(mm) < HEADER or mm[:len(RBASE_MAGIC)] != RBASE_MAGIC:
            return None
        if mm[len(RBASE_MAGIC):HEADER] != bytes.fromhex(dhash):
            return None
        buf = memoryview(mm)
        pos, off_expect, plain_total = HEADER, 0, 0
        base, nwords, nplain = [], [], []
        marks_off, rows_off, fb_off, fb_end = [], [], [], []
        totals = None
        while pos + FRAME_HEADER <= len(mm):
            magic = bytes(buf[pos:pos + 4])
            plen, crc = struct.unpack_from("<II", buf, pos + 4)
            start, end = pos + FRAME_HEADER, pos + FRAME_HEADER + plen
            if magic not in (FRAME_MAGIC, END_MAGIC) or end > len(mm):
                break
            if zlib.crc32(buf[start:end]) & 0xFFFFFFFF != crc:
                break
            if magic == END_MAGIC:
                if plen == 16:
                    totals = struct.unpack_from("<QQ", buf, start)
                break
            if plen < 16:
                break
            o, nw, npl = struct.unpack_from("<QII", buf, start)
            rows_at = start + 16 + nw + (-nw % 4)
            if o != off_expect or npl > nw or rows_at + 64 * npl > end:
                break
            base.append(o)
            nwords.append(nw)
            nplain.append(npl)
            marks_off.append(start + 16)
            rows_off.append(rows_at)
            fb_off.append(rows_at + 64 * npl)
            fb_end.append(end)
            off_expect = o + nw
            plain_total += npl
            pos = end
        if totals is None or totals != (off_expect, plain_total):
            return None
        return cls(mm, base, nwords, nplain, marks_off, rows_off,
                   fb_off, fb_end, off_expect, plain_total)

    def _fallback(self, k) -> list:
        """Decode chunk ``k``'s fallback words from the blob."""
        nfb = self._nwords[k] - self._nplain[k]
        out, p, end = [], self._fb_off[k], self._fb_end[k]
        for _ in range(nfb):
            if p + 4 > end:
                raise ValueError("rbase fallback blob truncated")
            (n,) = struct.unpack_from("<I", self._mm, p)
            p += 4
            if p + n > end:
                raise ValueError("rbase fallback blob truncated")
            out.append(self._mm[p:p + n])
            p += n
        return out

    def chunks(self, start: int = 0):
        """Yield ``(chunk_word_offset, marks uint8[nwords],
        rows u32[nplain, 16], fallback list)`` from the chunk containing
        word index ``start`` onward — marks/rows zero-copy, fallback
        decoded per served chunk (feed-producer work, DW111)."""
        i = max(0, bisect.bisect_right(self._base, start) - 1)
        for k in range(i, len(self._base)):
            nw, npl = self._nwords[k], self._nplain[k]
            marks = np.frombuffer(self._mm, np.uint8, nw, self._marks_off[k])
            rows = np.frombuffer(self._mm, "<u4", npl * 16,
                                 self._rows_off[k]).reshape(npl, 16)
            yield self._base[k], marks, rows, self._fallback(k)

    def close(self):
        """Eager unmap (tests only; see ``CachedDict.close``)."""
        if self._mm is not None:
            self._mm.close()
            self._mm = None


class RulesBaseWriter(DictCacheWriter):
    """Append-side of one dict's ``.rbase`` entry, fed by the rules
    feed's cold tee.  Same never-raises / cross-checked / atomic-commit
    contract as ``DictCacheWriter``; only the per-chunk payload
    differs (split + pack of the DEVICE-ELIGIBLE bases, fallback words
    verbatim)."""

    _MAGIC = RBASE_MAGIC

    def _flush(self, words):
        from ..native import pack_candidates_fast

        marks = np.empty(len(words), np.uint8)
        plain, fb = [], []
        for i, w in enumerate(words):
            # MUST match M22000Engine._rules_flush's split (framing
            # .rules_base_eligible): raw length, no $HEX decode
            if len(w) > _MAX_LEN or b"HEX[" in w:
                marks[i] = 0xFF
                fb.append(w)
            else:
                marks[i] = len(w)
                plain.append(w)
        rows_b = b""
        if plain:
            fast = pack_candidates_fast(plain, 0, _MAX_LEN,
                                        capacity=len(plain))
            if fast is None:
                raise RuntimeError("native packer unavailable")
            rows, plens, nvalid = fast
            # cross-check: the cache must reproduce the cold seam's
            # pack EXACTLY, or it must not exist
            if (nvalid != len(plain)
                    or not np.array_equal(
                        np.asarray(plens[:nvalid], np.uint8),
                        marks[marks != 0xFF])):
                raise RuntimeError("packer/lens-model disagreement")
            rows_b = rows[:nvalid].astype("<u4", copy=False).tobytes()
        blob = b"".join(struct.pack("<I", len(w)) + w for w in fb)
        payload = (struct.pack("<QII", self._off, len(words), len(plain))
                   + marks.tobytes() + b"\x00" * (-len(words) % 4)
                   + rows_b + blob + b"\x00" * (-len(blob) % 4))
        self._frame(FRAME_MAGIC, payload)
        self._off += len(words)
        self._nvalid += len(plain)


class DictCache:
    """Directory of per-dict packed cache files under a byte cap.

    ``reader(dhash)`` -> CachedDict | None (miss: absent, torn,
    corrupt, or keyed to different bytes); ``writer(dhash)`` ->
    DictCacheWriter | None (entry already complete, native packer
    unavailable, or a malformed key).  Eviction is whole-file,
    oldest-mtime first — a reader touch bumps mtime, so the policy is
    LRU over dicts.  All I/O is feed-producer work (lint rule DW111).
    """

    def __init__(self, root: str, max_bytes: int = 4 << 30, registry=None):
        self.root = root
        self.max_bytes = int(max_bytes)
        os.makedirs(root, exist_ok=True)
        from ..native import pack_candidates_fast

        # one probe: without the native packer the cold path never
        # produces packed rows, so there is nothing coherent to cache
        self._native_ok = pack_candidates_fast(
            [b"probeword0"], _MIN_LEN, _MAX_LEN, capacity=1) is not None
        if registry is None:
            from ..obs import default_registry

            registry = default_registry()
        self.m_hit_blocks = registry.counter(
            "dwpa_dictcache_hit_blocks_total",
            "candidate blocks served from the packed-dict cache").labels()
        self.m_miss_blocks = registry.counter(
            "dwpa_dictcache_miss_blocks_total",
            "candidate blocks cold-streamed past the packed-dict cache"
        ).labels()
        self._m_bytes = registry.gauge(
            "dwpa_dictcache_bytes",
            "total on-disk bytes of packed-dict cache entries").labels()
        rate = registry.gauge(
            "dwpa_dictcache_words_per_s",
            "dict words/s produced by the last warm/cold dict pass")
        self.m_words_warm = rate.labels(feed="warm")
        self.m_words_cold = rate.labels(feed="cold")
        self._m_bytes.set(float(self._bytes_used()))

    def _path(self, dhash: str, ext: str = ".dcache") -> str:
        return os.path.join(self.root, dhash + ext)

    def _open(self, dhash: str, ext: str, loader):
        if not dhash or not _DHASH_RE.fullmatch(dhash):
            return None
        path = self._path(dhash, ext)
        try:
            with open(path, "rb") as f:
                mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError):
            return None
        cd = loader(mm, dhash)
        if cd is None:
            mm.close()
            return None
        try:
            os.utime(path)
        except OSError:
            pass
        return cd

    def reader(self, dhash: str):
        """Open a complete cache entry for ``dhash``; None on any kind
        of miss.  Bumps the entry's mtime (LRU input for eviction)."""
        return self._open(dhash, ".dcache", CachedDict._load)

    def reader_rules(self, dhash: str):
        """Open a complete rules-base (``.rbase``) entry for ``dhash``;
        same miss/mtime semantics as ``reader``."""
        return self._open(dhash, ".rbase", CachedRulesBase._load)

    def _writer(self, dhash: str, ext: str, rd, cls):
        if not self._native_ok or not dhash or not _DHASH_RE.fullmatch(dhash):
            return None
        if rd is not None:
            return None          # complete entry: nothing to rewrite
        try:
            return cls(self, dhash, self._path(dhash, ext))
        except OSError:
            return None

    def writer(self, dhash: str):
        """Start (re)writing ``dhash``'s entry; None when a complete
        entry already exists, the key is malformed, or the native
        packer is unavailable."""
        return self._writer(dhash, ".dcache", self.reader(dhash),
                            DictCacheWriter)

    def writer_rules(self, dhash: str):
        """Start (re)writing ``dhash``'s rules-base entry; same
        preconditions as ``writer``."""
        return self._writer(dhash, ".rbase", self.reader_rules(dhash),
                            RulesBaseWriter)

    # -- size accounting / eviction ----------------------------------------

    def _entries(self):
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not name.endswith((".dcache", ".rbase")):
                continue
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((st.st_mtime_ns, st.st_size, path))
        return out

    def _bytes_used(self) -> int:
        return sum(size for _, size, _ in self._entries())

    def _committed(self):
        """Post-publish hook from a writer: refresh the gauge and
        enforce the byte cap."""
        self.evict()

    def evict(self):
        """Unlink oldest-mtime entries until the directory fits the
        cap.  An entry being actively served keeps working — POSIX
        keeps the mmap's pages alive after the unlink."""
        entries = sorted(self._entries())
        total = sum(size for _, size, _ in entries)
        for _, size, path in entries:
            if total <= self.max_bytes:
                break
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
        self._m_bytes.set(float(total))

    def close(self):
        """Nothing to flush — readers own their mmaps, writers are
        owned by the pass that opened them.  Kept for symmetry with
        the client's other stores."""
