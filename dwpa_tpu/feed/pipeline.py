"""Pipelined candidate feed: background producers ahead of the engine.

The paper's hot loop is "host feeds fixed-shape packed batches, device
runs PBKDF2" (SURVEY §5.1); until this subsystem, every candidate
reached the engine through synchronous generator chains — while the
host decoded/unhexed/packed block N the mesh sat idle, and while the
mesh cracked block N the host slept.  ``CandidateFeed`` moves the host
stages (dict streaming, rule expansion, ``$HEX`` decode +
``pack_candidates_fast`` packing) onto producer threads behind a
bounded block queue, so ``M22000Engine._prepare``'s packing cost is
paid off the critical path and starvation becomes measurable.

Design contracts:

- **Deterministic framing.**  Blocks are framed by ``framing.frame_blocks``
  — a pure function of the source stream and the ``(batch_size, nproc,
  pid)`` geometry — and delivered strictly in stream order, however many
  producer threads pack them.  Every block carries ``(offset, count)``
  global-stream coordinates, so the client's resume gate and the
  multi-host skip/count contracts are untouched by the threading.
- **Bounded + measured.**  At most ``depth`` framed blocks are in
  flight (framed-not-yet-consumed; packing producers can momentarily
  hold one block each beyond that).  A producer blocked on a full
  queue records ``dwpa_feed_producer_stall_seconds``; a consumer
  blocked on an empty one records ``dwpa_feed_consumer_starve_seconds``
  — the starve fraction is the headline "is the host keeping up"
  number (``bench:feed_overlap`` reports it next to PMK/s).
- **Producer thread discipline** (lint rule DW107): producer code runs
  pure host work — framing, byte wrangling, native packing — and may
  touch NO jax device API except ``device_put``/``shard_candidates``.
  Collectives, server calls, and resume-file writes belong to the
  consumer thread; the client hoists them before the feed starts
  (``_snapshot_prdict``/``_prefetch_cracked``/``_fetch_pass2_paths``).
- **Faults carry offsets.**  A producer exception is captured and
  re-raised at the consumer as ``FeedError`` with the global stream
  offset of the block being produced, so a crashed unit's checkpoint
  and the operator's log agree about where the stream broke.

Metric names (README "Candidate feed"): ``dwpa_feed_queue_depth``
(gauge), ``dwpa_feed_producer_stall_seconds`` /
``dwpa_feed_consumer_starve_seconds`` (histograms),
``dwpa_feed_blocks_total`` / ``dwpa_feed_candidates_total`` /
``dwpa_feed_bytes_total`` (counters) — all labeled ``feed=<name>`` —
plus ``feed:skip`` / ``feed:produce`` spans in ``dwpa_span_seconds``.
"""

import threading
import time

import jax

from ..obs import SpanTracer, default_registry, get_logger
from .framing import (
    frame_blocks, frame_packed, frame_rules_packed, skip_stream,
)


class FeedError(RuntimeError):
    """A producer failed; re-raised at the consumer with the global
    stream offset of the block it was producing."""

    def __init__(self, offset: int, cause: BaseException):
        super().__init__(
            f"candidate feed producer failed at stream offset {offset}: "
            f"{type(cause).__name__}: {cause}")
        self.offset = offset
        self.__cause__ = cause


class CandidateFeed:
    """Bounded, framed, optionally-prepacking candidate block queue.

    ``source``: the word iterable (consumed exactly once, in order).
    ``producers``: background threads (0 = inline/synchronous mode —
    same framing and prepacking, no threads; the multi-host-safe mode
    for sources that must stay on the consumer thread).
    ``skip``: resume fast-forward — consumed from the source before any
    framing; the actual count is ``feed.skipped`` and block offsets
    start at ``skip``.  ``nproc``/``pid`` (default: the jax process
    geometry) select sharded framing; ``prepack`` is an optional pure
    callable ``words -> (rows, lens, nvalid) | MixedPrep | None`` (see
    ``M22000Engine.host_packer``) run on the producer thread — with a
    PMK store attached it also performs the per-ESSID cache hit/miss
    split (``pmkstore.stage.split_block``), still pure host work.

    ``frames``: a pre-framed ``Block`` iterator (``DictFeedSource``)
    consumed INSTEAD of word framing — the source owns geometry and
    skip (pass ``skip=0``; warm cache skips are index lookups there,
    see ``feed.dictcache``).  Blocks arriving with a lazy prep
    (``framing.PackedSlices``) are materialized in ``_pack`` on the
    producer threads, then handed to a ``pre=``-aware ``prepack``
    (``host_packer``'s bypass) so the PMK-store hit/miss split still
    composes with cache-served blocks.
    """

    def __init__(self, source, batch_size: int, *, depth: int = 2,
                 producers: int = 1, skip: int = 0, nproc: int = None,
                 pid: int = None, pad_word: bytes = b"", prepack=None,
                 registry=None, name: str = "feed", frames=None):
        self.batch_size = int(batch_size)
        self.depth = max(1, int(depth))
        self.name = name
        self.prepack = prepack
        nproc = jax.process_count() if nproc is None else nproc
        pid = jax.process_index() if pid is None else pid
        self._skip = max(0, int(skip))
        self._skipped = 0
        self._skip_done = threading.Event()
        self._frontier = self._skip  # global offset of the framing edge
        if frames is not None:
            if self._skip:
                raise ValueError(
                    "frames= sources own their skip (pass skip=0)")
            self._src = iter(())
            self._frames = iter(frames)
        else:
            self._src = iter(source)
            self._frames = frame_blocks(self._src, self.batch_size,
                                        nproc=nproc, pid=pid,
                                        pad_word=pad_word,
                                        base_offset=self._skip)
        # _src_lock serializes source access (skip + framing); _cv guards
        # the reorder buffer, sequence counters and stop/fault state.
        # Producers take _src_lock then _cv; the consumer only ever takes
        # _cv — no lock-order cycle.
        self._src_lock = threading.Lock()
        self._cv = threading.Condition()
        self._buf = {}          # seq -> Block (packed, awaiting consumer)
        self._next_frame = 0    # next sequence number to frame
        self._next_get = 0      # next sequence number the consumer needs
        self._end_seq = None    # sequence count at stream exhaustion
        self._fault = None      # FeedError, delivered in stream order
        self._stop = False
        reg = registry or default_registry()
        self.tracer = SpanTracer(reg)
        lbl = {"feed": name}
        self._m_depth = reg.gauge(
            "dwpa_feed_queue_depth",
            "framed candidate blocks buffered ahead of the engine"
        ).labels(**lbl)
        self._m_stall = reg.histogram(
            "dwpa_feed_producer_stall_seconds",
            "per-block producer wait on a full feed queue (backpressure)"
        ).labels(**lbl)
        self._m_starve = reg.histogram(
            "dwpa_feed_consumer_starve_seconds",
            "per-block consumer wait on an empty feed queue (host too slow)"
        ).labels(**lbl)
        self._m_blocks = reg.counter(
            "dwpa_feed_blocks_total", "candidate blocks through the feed"
        ).labels(**lbl)
        self._m_cands = reg.counter(
            "dwpa_feed_candidates_total",
            "global candidates covered by feed blocks").labels(**lbl)
        self._m_bytes = reg.counter(
            "dwpa_feed_bytes_total",
            "candidate bytes materialized on this host").labels(**lbl)
        self._threads = []
        self._inline = producers <= 0
        if self._inline:
            # Inline mode: the consumer IS the producer, so the resume
            # fast-forward happens eagerly here — ``skipped`` must never
            # block on a thread that does not exist.
            self._do_skip()
        else:
            for k in range(int(producers)):
                t = threading.Thread(
                    target=self._produce, name=f"dwpa-feed-{name}-{k}",
                    daemon=True)
                t.start()
                self._threads.append(t)

    # -- producer side -----------------------------------------------------

    def _do_skip(self):
        """Resume fast-forward, once, before any framing (caller holds
        ``_src_lock`` in threaded mode)."""
        if self._skip_done.is_set():
            return
        try:
            if self._skip:
                with self.tracer.span("feed:skip"):
                    self._skipped = skip_stream(self._src, self._skip)
        finally:
            self._skip_done.set()

    def _frame_next(self):
        """-> (seq, Block | None) under ``_src_lock``; None = exhausted."""
        self._do_skip()
        blk = next(self._frames, None)
        seq = self._next_frame
        self._next_frame += 1
        if blk is not None:
            self._frontier = blk.offset + blk.count
        return seq, blk

    def _pack(self, blk):
        """Pure host work, off the consumer's critical path: byte
        accounting + native prepack.  NO jax device APIs here beyond
        what ``prepack`` itself stages (lint rule DW107)."""
        with self.tracer.span("feed:produce"):
            pre = blk.prep
            if pre is not None and hasattr(pre, "materialize"):
                # warm dict-cache block: copy the mmap-backed column
                # slices into the staged (rows, lens, nvalid) form here,
                # in parallel across producers; a pre-aware prepack
                # (host_packer's bypass) then composes the PMK-store
                # split without re-packing a single word
                blk.prep = pre = pre.materialize()
                self._m_bytes.inc(int(pre[1].sum()))
                if getattr(self.prepack, "supports_pre", False):
                    blk.prep = self.prepack(blk.words, pre=pre)
                return
            self._m_bytes.inc(blk.nbytes)
            if self.prepack is not None:
                blk.prep = self.prepack(blk.words)

    def _produce(self):
        blk = None
        try:
            while True:
                with self._src_lock:
                    # Backpressure BEFORE consuming the source: at most
                    # ``depth`` framed blocks in flight.
                    with self._cv:
                        while (not self._stop and self._fault is None
                               and self._next_frame
                               >= self._next_get + self.depth):
                            t0 = time.perf_counter()
                            self._cv.wait()
                            self._m_stall.observe(time.perf_counter() - t0)
                        if self._stop or self._fault is not None:
                            return
                    blk = None
                    seq, blk = self._frame_next()
                if blk is None:
                    with self._cv:
                        if self._end_seq is None or seq < self._end_seq:
                            self._end_seq = seq
                        self._cv.notify_all()
                    return
                self._pack(blk)
                with self._cv:
                    self._buf[seq] = blk
                    self._m_depth.set(len(self._buf))
                    self._cv.notify_all()
        except BaseException as e:  # noqa: BLE001 - delivered to consumer
            with self._cv:
                if self._fault is None:
                    # a framing fault breaks at the frontier; a packing
                    # fault breaks at the framed block's own offset
                    off = blk.offset if blk is not None else self._frontier
                    self._fault = FeedError(off, e)
                self._cv.notify_all()

    # -- consumer side -----------------------------------------------------

    @property
    def skipped(self) -> int:
        """Words actually consumed by the resume fast-forward (waits for
        the producer to finish the skip; it runs before any framing)."""
        self._skip_done.wait()
        return self._skipped

    def __iter__(self):
        return self

    def __next__(self):
        if self._inline:
            return self._record(self._next_inline())
        t0 = time.perf_counter()
        with self._cv:
            seq = self._next_get
            while seq not in self._buf:
                if self._fault is not None:
                    raise self._fault
                if self._end_seq is not None and seq >= self._end_seq:
                    raise StopIteration
                self._cv.wait()
            self._m_starve.observe(time.perf_counter() - t0)
            blk = self._buf.pop(seq)
            self._next_get = seq + 1
            self._m_depth.set(len(self._buf))
            self._cv.notify_all()
        return self._record(blk)

    def _next_inline(self):
        blk = None
        try:
            seq, blk = self._frame_next()
            if blk is None:
                raise StopIteration
            self._pack(blk)
        except StopIteration:
            raise
        except BaseException as e:  # mirror the threaded fault contract
            raise FeedError(
                blk.offset if blk is not None else self._frontier, e) from e
        self._next_get = seq + 1
        return blk

    def _record(self, blk):
        self._m_blocks.inc()
        self._m_cands.inc(blk.count)
        return blk

    def words(self):
        """Flat word-stream view, in global stream order — the base-word
        feed for ``M22000Engine.crack_rules`` (which owns its own global
        framing and packing; use ``prepack=None`` and the default
        single-host framing with this view)."""
        for blk in self:
            yield from blk.words

    # -- lifecycle ---------------------------------------------------------

    def close(self, timeout: float = 10.0):
        """Stop producers and join them.  Idempotent; safe after a
        consumer break, a fault, or normal exhaustion.  A producer
        blocked inside a slow source read is a daemon thread and is
        abandoned at the timeout (it exits at its next checkpoint)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)
        self._skip_done.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


#: a cold skip larger than this replays the gzip prefix long enough to
#: matter — logged once per unit so the operator knows the O(skip)
#: hazard fired (the warm path never does: cache skips are index seeks)
SKIP_REPLAY_WARN = 1_000_000

#: words buffered per cache-writer hand-off on the cold tee
_TEE_WORDS = 4096


class DictFeedSource:
    """Framed block source over a unit's dict files — warm where the
    packed cache has them, cold (with cache write-back) where not.

    The warm-source adapter of ``feed.dictcache``: feed it to
    ``CandidateFeed(frames=...)``.  Each dict is framed SEPARATELY
    (offsets stay global and contiguous across dicts), so every host
    derives the same ``(offset, count)`` block geometry from the dict
    word counts alone — a mesh where one host is cache-warm and
    another cold still frames identically, which is what keeps the
    SPMD-lockstep and resume contracts cache-state-independent.

    ``units``: ``[(path, dhash | None)]`` in stream order (a None
    dhash is never cached).  ``skip`` is the GLOBAL resume
    fast-forward: warm dicts satisfy it with an index seek (O(1));
    cold dicts replay the prefix (today's semantics) and log once per
    unit past ``SKIP_REPLAY_WARN`` words.  ``skipped`` reports the
    words actually consumed by the skip, exactly like
    ``CandidateFeed.skipped``.

    Iteration is driven from the feed's producer side (under its
    source lock), so cache reads/writes stay on producer threads —
    lint rule DW111's discipline, same shape as DW107/DW108.
    """

    def __init__(self, units, batch_size: int, *, cache=None,
                 nproc: int = None, pid: int = None, pad_word: bytes = b"",
                 skip: int = 0, name: str = "feed", log=None):
        self.units = list(units)
        self.batch_size = int(batch_size)
        self.cache = cache
        self.nproc = jax.process_count() if nproc is None else nproc
        self.pid = jax.process_index() if pid is None else pid
        self.pad_word = pad_word
        self.name = name
        self.skipped = 0
        self._skip = max(0, int(skip))
        self._log = log or get_logger("feed").info

    def _tee(self, stream, wr):
        """Pass words through to the framer while batching them into
        the cache writer; commits on full-stream exhaustion (a partial
        consume is aborted by the iterator's finally)."""
        buf = []
        for w in stream:
            buf.append(w)
            if len(buf) >= _TEE_WORDS:
                wr.add_many(buf)
                buf = []
            yield w
        wr.add_many(buf)
        wr.commit()

    def __iter__(self):
        cache = self.cache
        offset = 0            # global stream position (skipped + served)
        remaining = self._skip
        warned = False
        for path, dhash in self.units:
            rd = cache.reader(dhash) if cache is not None else None
            if rd is not None:
                # -- warm: mmap'd packed blocks, zero gunzip ------------
                total = rd.total_words
                if remaining >= total:
                    # whole dict inside the resume window: pure index
                    # math, nothing decompressed, nothing replayed
                    remaining -= total
                    self.skipped += total
                    offset += total
                    continue
                start = remaining
                self.skipped += start
                remaining = 0
                t0 = time.perf_counter()
                served = 0
                for blk in frame_packed(rd.chunks(start), total,
                                        self.batch_size, nproc=self.nproc,
                                        pid=self.pid,
                                        base_offset=offset + start,
                                        start=start):
                    cache.m_hit_blocks.inc()
                    served += blk.count
                    yield blk
                el = time.perf_counter() - t0
                if served and el > 0:
                    cache.m_words_warm.set(served / el)
                offset += total
                continue
            # -- cold: gunzip stream; write the cache alongside --------
            from ..gen.dicts import DictStream

            stream = iter(DictStream(path))
            if remaining:
                if remaining > SKIP_REPLAY_WARN and not warned:
                    warned = True
                    self._log(
                        f"feed {self.name}: cold dict skip replays "
                        f"{remaining} words (O(skip) gzip prefix; a warm "
                        f"dict cache would seek the block index instead)")
                k = skip_stream(stream, remaining)
                self.skipped += k
                offset += k
                remaining -= k
                if remaining:
                    continue      # dict exhausted inside the skip window
            # cache only FULL streams from word 0 — the framer consumes
            # every source word even when slicing for one host, so the
            # tee sees the complete dict on any mesh
            wr = cache.writer(dhash) if cache is not None else None
            src = stream if wr is None else self._tee(stream, wr)
            t0 = time.perf_counter()
            served = 0
            try:
                for blk in frame_blocks(src, self.batch_size,
                                        nproc=self.nproc, pid=self.pid,
                                        pad_word=self.pad_word,
                                        base_offset=offset):
                    if cache is not None:
                        cache.m_miss_blocks.inc()
                    served += blk.count
                    offset = blk.offset + blk.count
                    yield blk
            finally:
                if wr is not None:
                    wr.abort()    # no-op after the tee's commit
            el = time.perf_counter() - t0
            if cache is not None and served and el > 0:
                cache.m_words_cold.set(served / el)


class RulesFeedSource:
    """Framed BASE-WORD block source for the device rule-expansion
    path (``M22000Engine.crack_rules_blocks`` /
    ``crack_rules_streams``) — warm where the ``.rbase`` cache has the
    dict, cold (with ``.rbase`` write-back) where not.

    The rules twin of ``DictFeedSource``.  Warm dicts serve
    ``feed.framing.RulesPrep`` blocks (split + pack memoized; the
    engine seam skips straight to H2D), cold dicts serve raw word
    blocks while the tee writes the entry for next time.  Both sides
    emit the identical ``frame_blocks`` ``(offset, count)`` geometry
    over the same raw word stream, so a mid-pass warm/cold transition
    (or a resume across one) cannot shift the expansion order.

    ``skip`` counts BASE WORDS (not expanded pairs): the engine's
    expanded resume window is handled by its own skip argument; this
    source-level skip exists for whole-dict fast-forwarding, and warm
    dicts satisfy it with an index seek.  Single-process framing only —
    multi-host rules attacks keep the flat ``crack_rules`` path
    (``CandidateFeed.words``).
    """

    def __init__(self, units, batch_size: int, *, cache=None,
                 skip: int = 0, name: str = "pass2", log=None):
        self.units = list(units)
        self.batch_size = int(batch_size)
        self.cache = cache
        self.name = name
        self.skipped = 0
        self._skip = max(0, int(skip))
        self._log = log or get_logger("feed").info

    def _tee(self, stream, wr):
        buf = []
        for w in stream:
            buf.append(w)
            if len(buf) >= _TEE_WORDS:
                wr.add_many(buf)
                buf = []
            yield w
        wr.add_many(buf)
        wr.commit()

    def __iter__(self):
        cache = self.cache
        offset = 0
        remaining = self._skip
        warned = False
        for path, dhash in self.units:
            rd = cache.reader_rules(dhash) if cache is not None else None
            if rd is not None:
                # -- warm: mmap'd pre-split base blocks ------------------
                total = rd.total_words
                if remaining >= total:
                    remaining -= total
                    self.skipped += total
                    offset += total
                    continue
                start = remaining
                self.skipped += start
                remaining = 0
                t0 = time.perf_counter()
                served = 0
                for blk in frame_rules_packed(rd.chunks(start), total,
                                              self.batch_size,
                                              base_offset=offset + start,
                                              start=start):
                    cache.m_hit_blocks.inc()
                    served += blk.count
                    yield blk
                el = time.perf_counter() - t0
                if served and el > 0:
                    cache.m_words_warm.set(served / el)
                offset += total
                continue
            # -- cold: gunzip stream; write the rules base alongside ----
            from ..gen.dicts import DictStream

            stream = iter(DictStream(path))
            if remaining:
                if remaining > SKIP_REPLAY_WARN and not warned:
                    warned = True
                    self._log(
                        f"feed {self.name}: cold dict skip replays "
                        f"{remaining} words (O(skip) gzip prefix; a warm "
                        f"rules-base cache would seek the block index "
                        f"instead)")
                k = skip_stream(stream, remaining)
                self.skipped += k
                offset += k
                remaining -= k
                if remaining:
                    continue
            wr = cache.writer_rules(dhash) if cache is not None else None
            src = stream if wr is None else self._tee(stream, wr)
            t0 = time.perf_counter()
            served = 0
            try:
                for blk in frame_blocks(src, self.batch_size,
                                        base_offset=offset):
                    if cache is not None:
                        cache.m_miss_blocks.inc()
                    served += blk.count
                    offset = blk.offset + blk.count
                    yield blk
            finally:
                if wr is not None:
                    wr.abort()
            el = time.perf_counter() - t0
            if cache is not None and served and el > 0:
                cache.m_words_cold.set(served / el)
