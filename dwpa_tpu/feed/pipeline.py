"""Pipelined candidate feed: background producers ahead of the engine.

The paper's hot loop is "host feeds fixed-shape packed batches, device
runs PBKDF2" (SURVEY §5.1); until this subsystem, every candidate
reached the engine through synchronous generator chains — while the
host decoded/unhexed/packed block N the mesh sat idle, and while the
mesh cracked block N the host slept.  ``CandidateFeed`` moves the host
stages (dict streaming, rule expansion, ``$HEX`` decode +
``pack_candidates_fast`` packing) onto producer threads behind a
bounded block queue, so ``M22000Engine._prepare``'s packing cost is
paid off the critical path and starvation becomes measurable.

Design contracts:

- **Deterministic framing.**  Blocks are framed by ``framing.frame_blocks``
  — a pure function of the source stream and the ``(batch_size, nproc,
  pid)`` geometry — and delivered strictly in stream order, however many
  producer threads pack them.  Every block carries ``(offset, count)``
  global-stream coordinates, so the client's resume gate and the
  multi-host skip/count contracts are untouched by the threading.
- **Bounded + measured.**  At most ``depth`` framed blocks are in
  flight (framed-not-yet-consumed; packing producers can momentarily
  hold one block each beyond that).  A producer blocked on a full
  queue records ``dwpa_feed_producer_stall_seconds``; a consumer
  blocked on an empty one records ``dwpa_feed_consumer_starve_seconds``
  — the starve fraction is the headline "is the host keeping up"
  number (``bench:feed_overlap`` reports it next to PMK/s).
- **Producer thread discipline** (lint rule DW107): producer code runs
  pure host work — framing, byte wrangling, native packing — and may
  touch NO jax device API except ``device_put``/``shard_candidates``.
  Collectives, server calls, and resume-file writes belong to the
  consumer thread; the client hoists them before the feed starts
  (``_snapshot_prdict``/``_prefetch_cracked``/``_fetch_pass2_paths``).
- **Faults carry offsets.**  A producer exception is captured and
  re-raised at the consumer as ``FeedError`` with the global stream
  offset of the block being produced, so a crashed unit's checkpoint
  and the operator's log agree about where the stream broke.

Metric names (README "Candidate feed"): ``dwpa_feed_queue_depth``
(gauge), ``dwpa_feed_producer_stall_seconds`` /
``dwpa_feed_consumer_starve_seconds`` (histograms),
``dwpa_feed_blocks_total`` / ``dwpa_feed_candidates_total`` /
``dwpa_feed_bytes_total`` (counters) — all labeled ``feed=<name>`` —
plus ``feed:skip`` / ``feed:produce`` spans in ``dwpa_span_seconds``.
"""

import threading
import time

import jax

from ..obs import SpanTracer, default_registry
from .framing import frame_blocks, skip_stream


class FeedError(RuntimeError):
    """A producer failed; re-raised at the consumer with the global
    stream offset of the block it was producing."""

    def __init__(self, offset: int, cause: BaseException):
        super().__init__(
            f"candidate feed producer failed at stream offset {offset}: "
            f"{type(cause).__name__}: {cause}")
        self.offset = offset
        self.__cause__ = cause


class CandidateFeed:
    """Bounded, framed, optionally-prepacking candidate block queue.

    ``source``: the word iterable (consumed exactly once, in order).
    ``producers``: background threads (0 = inline/synchronous mode —
    same framing and prepacking, no threads; the multi-host-safe mode
    for sources that must stay on the consumer thread).
    ``skip``: resume fast-forward — consumed from the source before any
    framing; the actual count is ``feed.skipped`` and block offsets
    start at ``skip``.  ``nproc``/``pid`` (default: the jax process
    geometry) select sharded framing; ``prepack`` is an optional pure
    callable ``words -> (rows, lens, nvalid) | MixedPrep | None`` (see
    ``M22000Engine.host_packer``) run on the producer thread — with a
    PMK store attached it also performs the per-ESSID cache hit/miss
    split (``pmkstore.stage.split_block``), still pure host work.
    """

    def __init__(self, source, batch_size: int, *, depth: int = 2,
                 producers: int = 1, skip: int = 0, nproc: int = None,
                 pid: int = None, pad_word: bytes = b"", prepack=None,
                 registry=None, name: str = "feed"):
        self.batch_size = int(batch_size)
        self.depth = max(1, int(depth))
        self.name = name
        self.prepack = prepack
        nproc = jax.process_count() if nproc is None else nproc
        pid = jax.process_index() if pid is None else pid
        self._skip = max(0, int(skip))
        self._skipped = 0
        self._skip_done = threading.Event()
        self._src = iter(source)
        self._frontier = self._skip  # global offset of the framing edge
        self._frames = frame_blocks(self._src, self.batch_size, nproc=nproc,
                                    pid=pid, pad_word=pad_word,
                                    base_offset=self._skip)
        # _src_lock serializes source access (skip + framing); _cv guards
        # the reorder buffer, sequence counters and stop/fault state.
        # Producers take _src_lock then _cv; the consumer only ever takes
        # _cv — no lock-order cycle.
        self._src_lock = threading.Lock()
        self._cv = threading.Condition()
        self._buf = {}          # seq -> Block (packed, awaiting consumer)
        self._next_frame = 0    # next sequence number to frame
        self._next_get = 0      # next sequence number the consumer needs
        self._end_seq = None    # sequence count at stream exhaustion
        self._fault = None      # FeedError, delivered in stream order
        self._stop = False
        reg = registry or default_registry()
        self.tracer = SpanTracer(reg)
        lbl = {"feed": name}
        self._m_depth = reg.gauge(
            "dwpa_feed_queue_depth",
            "framed candidate blocks buffered ahead of the engine"
        ).labels(**lbl)
        self._m_stall = reg.histogram(
            "dwpa_feed_producer_stall_seconds",
            "per-block producer wait on a full feed queue (backpressure)"
        ).labels(**lbl)
        self._m_starve = reg.histogram(
            "dwpa_feed_consumer_starve_seconds",
            "per-block consumer wait on an empty feed queue (host too slow)"
        ).labels(**lbl)
        self._m_blocks = reg.counter(
            "dwpa_feed_blocks_total", "candidate blocks through the feed"
        ).labels(**lbl)
        self._m_cands = reg.counter(
            "dwpa_feed_candidates_total",
            "global candidates covered by feed blocks").labels(**lbl)
        self._m_bytes = reg.counter(
            "dwpa_feed_bytes_total",
            "candidate bytes materialized on this host").labels(**lbl)
        self._threads = []
        self._inline = producers <= 0
        if self._inline:
            # Inline mode: the consumer IS the producer, so the resume
            # fast-forward happens eagerly here — ``skipped`` must never
            # block on a thread that does not exist.
            self._do_skip()
        else:
            for k in range(int(producers)):
                t = threading.Thread(
                    target=self._produce, name=f"dwpa-feed-{name}-{k}",
                    daemon=True)
                t.start()
                self._threads.append(t)

    # -- producer side -----------------------------------------------------

    def _do_skip(self):
        """Resume fast-forward, once, before any framing (caller holds
        ``_src_lock`` in threaded mode)."""
        if self._skip_done.is_set():
            return
        try:
            if self._skip:
                with self.tracer.span("feed:skip"):
                    self._skipped = skip_stream(self._src, self._skip)
        finally:
            self._skip_done.set()

    def _frame_next(self):
        """-> (seq, Block | None) under ``_src_lock``; None = exhausted."""
        self._do_skip()
        blk = next(self._frames, None)
        seq = self._next_frame
        self._next_frame += 1
        if blk is not None:
            self._frontier = blk.offset + blk.count
        return seq, blk

    def _pack(self, blk):
        """Pure host work, off the consumer's critical path: byte
        accounting + native prepack.  NO jax device APIs here beyond
        what ``prepack`` itself stages (lint rule DW107)."""
        with self.tracer.span("feed:produce"):
            self._m_bytes.inc(blk.nbytes)
            if self.prepack is not None:
                blk.prep = self.prepack(blk.words)

    def _produce(self):
        blk = None
        try:
            while True:
                with self._src_lock:
                    # Backpressure BEFORE consuming the source: at most
                    # ``depth`` framed blocks in flight.
                    with self._cv:
                        while (not self._stop and self._fault is None
                               and self._next_frame
                               >= self._next_get + self.depth):
                            t0 = time.perf_counter()
                            self._cv.wait()
                            self._m_stall.observe(time.perf_counter() - t0)
                        if self._stop or self._fault is not None:
                            return
                    blk = None
                    seq, blk = self._frame_next()
                if blk is None:
                    with self._cv:
                        if self._end_seq is None or seq < self._end_seq:
                            self._end_seq = seq
                        self._cv.notify_all()
                    return
                self._pack(blk)
                with self._cv:
                    self._buf[seq] = blk
                    self._m_depth.set(len(self._buf))
                    self._cv.notify_all()
        except BaseException as e:  # noqa: BLE001 - delivered to consumer
            with self._cv:
                if self._fault is None:
                    # a framing fault breaks at the frontier; a packing
                    # fault breaks at the framed block's own offset
                    off = blk.offset if blk is not None else self._frontier
                    self._fault = FeedError(off, e)
                self._cv.notify_all()

    # -- consumer side -----------------------------------------------------

    @property
    def skipped(self) -> int:
        """Words actually consumed by the resume fast-forward (waits for
        the producer to finish the skip; it runs before any framing)."""
        self._skip_done.wait()
        return self._skipped

    def __iter__(self):
        return self

    def __next__(self):
        if self._inline:
            return self._record(self._next_inline())
        t0 = time.perf_counter()
        with self._cv:
            seq = self._next_get
            while seq not in self._buf:
                if self._fault is not None:
                    raise self._fault
                if self._end_seq is not None and seq >= self._end_seq:
                    raise StopIteration
                self._cv.wait()
            self._m_starve.observe(time.perf_counter() - t0)
            blk = self._buf.pop(seq)
            self._next_get = seq + 1
            self._m_depth.set(len(self._buf))
            self._cv.notify_all()
        return self._record(blk)

    def _next_inline(self):
        blk = None
        try:
            seq, blk = self._frame_next()
            if blk is None:
                raise StopIteration
            self._pack(blk)
        except StopIteration:
            raise
        except BaseException as e:  # mirror the threaded fault contract
            raise FeedError(
                blk.offset if blk is not None else self._frontier, e) from e
        self._next_get = seq + 1
        return blk

    def _record(self, blk):
        self._m_blocks.inc()
        self._m_cands.inc(blk.count)
        return blk

    def words(self):
        """Flat word-stream view, in global stream order — the base-word
        feed for ``M22000Engine.crack_rules`` (which owns its own global
        framing and packing; use ``prepack=None`` and the default
        single-host framing with this view)."""
        for blk in self:
            yield from blk.words

    # -- lifecycle ---------------------------------------------------------

    def close(self, timeout: float = 10.0):
        """Stop producers and join them.  Idempotent; safe after a
        consumer break, a fault, or normal exhaustion.  A producer
        blocked inside a slow source read is a daemon thread and is
        abandoned at the timeout (it exits at its next checkpoint)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)
        self._skip_done.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
