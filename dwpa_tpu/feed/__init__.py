"""dwpa_tpu.feed — the pipelined candidate-feed subsystem.

Overlaps host candidate production with device compute, the input
pipeline the ROADMAP's "as fast as the hardware allows" north star
calls for:

- :mod:`.framing` — deterministic ``(global_offset, count)`` block
  framing (single-host and multi-host shard slicing; the resume-gate
  and SPMD-lockstep contracts live here), plus ``frame_packed`` — the
  index-backed twin that frames mmap'd dict-cache chunks into the
  same geometry with lazy ``PackedSlices`` preps;
- :mod:`.pipeline` — ``CandidateFeed``: bounded block queue filled by
  producer threads running the host stages (dict streaming, rule
  expansion, ``$HEX`` decode + native packing), with backpressure,
  fault-with-offset delivery, and ``dwpa_feed_*`` telemetry; and
  ``DictFeedSource`` / ``RulesFeedSource`` — the warm/cold dict
  adapters for ``CandidateFeed(frames=...)`` (candidate blocks for
  pass 1; compact base-word blocks for the on-device rule-expansion
  pass 2);
- :mod:`.dictcache` — ``DictCache``: the persistent packed-dictionary
  cache (CRC-framed chunks keyed by dhash, O(1) ``(offset, count)``
  seek, byte-capped LRU eviction) the warm path serves from — two
  species per dict: ``.dcache`` (decoded candidate rows) and
  ``.rbase`` (rule-expansion base blocks, split + pack memoized);
- :mod:`.staging` — ``DeviceStager``: double-buffered ``shard_candidates``
  H2D, enqueueing block N+1's upload while block N's steps execute.

Consumed by ``M22000Engine.crack_blocks`` and wired through the client
(pass 1, both pass-2 paths, prewarm) and ``bench:feed_overlap`` /
``bench:dict_cache``.
"""

from .dictcache import DictCache
from .framing import Block, PackedSlices, RulesPrep, frame_blocks, \
    frame_packed, frame_rules_packed, skip_stream
from .pipeline import CandidateFeed, DictFeedSource, FeedError, \
    RulesFeedSource
from .staging import DeviceStager

__all__ = [
    "Block", "PackedSlices", "RulesPrep", "frame_blocks", "frame_packed",
    "frame_rules_packed", "skip_stream", "CandidateFeed", "DictFeedSource",
    "FeedError", "RulesFeedSource", "DeviceStager", "DictCache",
]
