"""Deterministic candidate-block framing for the feed subsystem.

Every block of the candidate pipeline carries ``(offset, count)`` — the
GLOBAL stream position of its first candidate and the number of global
candidates it covers — so the client's resume gate (skip-by-count
against ``_write_resume``'s ``_batch``/mesh/version stamps) and the
multi-host lockstep contract keep working unchanged when production
moves onto background threads: the framing is a pure function of the
source stream and the ``(batch_size, nproc, pid)`` geometry, never of
producer/consumer timing.

Multi-host framing preserves the exact slicing contract of the former
``client.main.shard_word_blocks`` (which now delegates here): per
global block of up to ``batch_size * nproc`` words,
``blk = min(batch_size, ceil(n / nproc))`` and this host's slice is
``block[pid * blk:(pid + 1) * blk]`` padded to ``blk`` with an invalid
word — every host emits the SAME number of same-shaped blocks (the
SPMD-lockstep requirement of ``M22000Engine.crack``), and an empty
shard becomes an all-padding block (``Block.padded``) rather than an
absent one.

Unlike the old slicer, a host no longer materializes the full
``batch_size * nproc`` global block: only words whose index can still
fall inside this host's slice are buffered.  Because
``blk(n) = min(batch_size, ceil(n / nproc))`` is nondecreasing in the
final block length ``n``, the slice window only ever moves right as the
block grows — so a word at block-index ``i`` is kept iff
``pid * blk(i + 1) <= i < (pid + 1) * batch_size``, and the buffer's
left edge is pruned to ``pid * blk(c)`` as the consumed count ``c``
grows.  Peak buffering is ``(pid + 1) * batch_size - pid * blk(c)``
(<= ``(pid + 1)(nproc - pid)/nproc * batch_size``, exactly
``batch_size`` for full blocks and for host 0) versus the former
``batch_size * nproc`` on every host.
"""

import itertools
from collections import deque
from dataclasses import dataclass

import numpy as np


@dataclass
class Block:
    """One framed candidate block.

    ``offset``/``count`` index the GLOBAL source stream (resume
    checkpoints advance by ``count``); ``words`` is this host's local
    slice (equal to the global block when ``nproc == 1``).  ``prep``
    is filled by a prepacking producer (see ``CandidateFeed``):
    ``(rows uint32[cap, 16], lens uint8[nvalid], nvalid)`` — the
    host-packed form ``M22000Engine._prepare_staged`` stages to the
    device without re-packing — or a ``pmkstore.stage.MixedPrep`` when
    the packer is PMK-store-aware (the block pre-split into cache hits
    and misses, ``M22000Engine._prepare_mixed``).  ``padded`` marks an
    all-padding block
    (this host's shard of the global block was empty — dispatched
    anyway to keep the slice in lockstep, see ``_padding_prep``).
    """

    offset: int
    count: int
    words: list
    prep: tuple = None
    padded: bool = False

    @property
    def nbytes(self) -> int:
        return sum(len(w) for w in self.words)


def _blk(n: int, batch_size: int, nproc: int) -> int:
    """Per-host slice width for a global block of ``n`` words."""
    return min(batch_size, -(-n // nproc))


def frame_blocks(words, batch_size: int, nproc: int = 1, pid: int = 0,
                 pad_word: bytes = b"", base_offset: int = 0,
                 watermark: list = None):
    """Frame a global word stream into deterministic ``Block``s,
    materializing only this host's 1/nproc shard slice (module
    docstring has the exact contract and the buffering bound).

    ``base_offset`` seeds the global offset (a resume fast-forward that
    already consumed ``skip`` words passes ``skip``).  ``watermark``
    (tests only) receives each block's peak buffer size.
    """
    it = iter(words)
    gsize = batch_size * nproc
    hi = (pid + 1) * batch_size
    offset = base_offset
    while True:
        buf = deque()  # (block-index, word), indices strictly increasing
        peak = c = 0
        for w in it:
            i = c
            c += 1
            if i < hi and pid * _blk(i + 1, batch_size, nproc) <= i:
                buf.append((i, w))
            # prune the left edge: the final window start can only grow
            start = pid * _blk(c, batch_size, nproc)
            while buf and buf[0][0] < start:
                buf.popleft()
            peak = max(peak, len(buf))
            if c == gsize:
                break
        if watermark is not None and c:
            watermark.append(peak)
        if c == 0:
            return
        blk = _blk(c, batch_size, nproc)
        start = pid * blk
        mine = [w for i, w in buf if i < start + blk]
        nreal = len(mine)
        mine += [pad_word] * (blk - nreal)
        yield Block(offset=offset, count=c, words=mine, padded=(nreal == 0))
        offset += c
        if c < gsize:
            return


class PackedSlices:
    """Lazy prep for a warm dict-cache block: zero-copy ``(lens, rows)``
    column windows into mmap'd cache chunks, materialized into the
    ``(rows uint32[cap, 16], lens uint8[nvalid], nvalid)`` staged form
    on a feed producer thread (``CandidateFeed._pack``) — the memcpy
    out of the page cache happens off the consumer's critical path,
    and N producers can materialize disjoint block ranges in parallel
    (the mmap is read-only and shared).

    ``materialize()`` reproduces EXACTLY what ``pack_candidates_fast``
    returns for the block's word slice on the cold path: accepted rows
    contiguous from 0 in stream order, zero rows beyond ``nvalid``,
    ``cap == batch_size`` (a host slice is never wider than one batch).
    """

    __slots__ = ("parts", "cap")

    def __init__(self, parts, cap: int):
        self.parts = parts   # [(lens uint8[k] view, rows u32[nv, 16] view)]
        self.cap = cap

    def materialize(self):
        packed = np.zeros((self.cap, 16), np.uint32)
        lens, r = [], 0
        for lens_all, rows in self.parts:
            nv = rows.shape[0]
            if nv:
                packed[r:r + nv] = rows
                lens.append(lens_all[lens_all > 0])
                r += nv
        lens = (np.concatenate(lens) if lens else np.zeros(0, np.uint8))
        return packed, lens, r


class RulesPrep:
    """Warm base-word block for the device rule-expansion seam
    (``M22000Engine._rules_flush``): the split rule sets, the expanded
    split into device-eligible bases vs host-fallback words, and the
    native pack already ran (and were cached) — the seam skips straight
    to the H2D upload.  ``rows``/``lens`` are the packed device layout
    of the ``nplain`` eligible bases in stream order (rows exactly what
    ``pack_candidates_fast(plain, 0, MAX_PSK_LEN)`` produces, lens the
    RAW byte lengths — rule semantics see the undecoded word);
    ``fallback`` is the block's ineligible words (> 63 bytes or
    ``HEX[`` carriers), also in stream order, routed to the host
    interpreter for every rule.  The ``rules_base`` class attribute is
    the marker the seam duck-types on.
    """

    __slots__ = ("rows", "lens", "nplain", "fallback")

    rules_base = True

    def __init__(self, rows, lens, nplain, fallback):
        self.rows = rows
        self.lens = lens
        self.nplain = nplain
        self.fallback = fallback

    def padded_rows(self, cap: int):
        """Rows zero-padded to the engine's ``cap`` — the warm twin of
        the seam's cold ``pack_candidates_fast(..., capacity=cap)``
        call (always a fresh native-endian array: the stored rows may
        be a read-only little-endian mmap view)."""
        out = np.zeros((cap, 16), np.uint32)
        out[:self.nplain] = self.rows[:self.nplain]
        return out


def rules_base_eligible(w: bytes) -> bool:
    """The device-expansion split predicate (must match
    ``M22000Engine._rules_flush``): overlong bases and anything that
    could put ``$HEX[...]`` syntax in front of the engine's unhex stage
    go to the host interpreter."""
    return len(w) <= 63 and b"HEX[" not in w


def frame_rules_packed(chunks, total: int, batch_size: int,
                       base_offset: int = 0, start: int = 0):
    """Frame a warm rules-base cache range into ``Block``s — the
    ``.rbase`` twin of ``frame_packed``: identical ``(offset, count)``
    geometry to ``frame_blocks`` over the same raw word stream
    (single-process framing; multi-host rules attacks keep the flat
    ``crack_rules`` path), with ``Block.prep`` carrying an eager
    ``RulesPrep`` instead of words.

    ``chunks`` yields ``(chunk_word_offset, marks uint8[nwords],
    rows u32[nplain, 16], fallback list)`` views
    (``dictcache.CachedRulesBase.chunks(start)``); ``marks[i]`` is the
    base length of word ``offset + i`` or ``0xFF`` for a fallback
    word.  ``start``/``base_offset`` follow ``frame_packed``.
    """
    it = iter(chunks)
    cur = None     # (chunk base, marks, rows, fb, plain-cumsum, fb-cumsum)
    pos = start
    while pos < total:
        c = min(batch_size, total - pos)
        lo, hi = pos, pos + c
        lens_parts, rows_parts, fbs = [], [], []
        a = lo
        while a < hi:
            while cur is None or cur[0] + len(cur[1]) <= a:
                cbase, marks, rows, fb = next(it)
                cur = (cbase, marks, rows, fb,
                       np.cumsum(marks != 0xFF), np.cumsum(marks == 0xFF))
            cbase, marks, rows, fb, pcum, fcum = cur
            b = min(hi, cbase + len(marks))
            i, j = a - cbase, b - cbase
            ps = int(pcum[i - 1]) if i else 0
            pe = int(pcum[j - 1]) if j else 0
            fs = int(fcum[i - 1]) if i else 0
            fe = int(fcum[j - 1]) if j else 0
            m = marks[i:j]
            lens_parts.append(m[m != 0xFF])
            rows_parts.append(rows[ps:pe])
            fbs.extend(fb[fs:fe])
            a = b
        lens = (np.concatenate(lens_parts) if lens_parts
                else np.zeros(0, np.uint8))
        nplain = len(lens)
        packed = np.zeros((nplain, 16), np.uint32)
        r = 0
        for rp in rows_parts:
            packed[r:r + len(rp)] = rp
            r += len(rp)
        yield Block(offset=base_offset + (pos - start), count=c, words=[],
                    prep=RulesPrep(packed, lens, nplain, fbs),
                    padded=(c == 0))
        pos += c


def frame_packed(chunks, total: int, batch_size: int, nproc: int = 1,
                 pid: int = 0, base_offset: int = 0, start: int = 0):
    """Frame a warm packed-dict word range into ``Block``s — the
    index-backed twin of ``frame_blocks``: identical ``(offset, count,
    padded)`` geometry for the same word stream and ``(batch_size,
    nproc, pid)``, but driven by the cache's chunk index instead of the
    decoded words (``Block.words`` stays empty; ``Block.prep`` carries
    a lazy ``PackedSlices``).

    ``chunks`` yields ``(chunk_word_offset, lens, rows)`` views
    (``CachedDict.chunks(start)``); ``total`` is the dict's word count;
    ``start`` is the first word index to serve (a resume/shard seek —
    an index lookup, not a prefix replay); ``base_offset`` is the
    GLOBAL stream offset of word ``start``.
    """
    it = iter(chunks)
    cur = None               # (chunk base, lens view, valid-cumsum, rows)
    gsize = batch_size * nproc
    pos = start
    while pos < total:
        c = min(gsize, total - pos)
        blk = _blk(c, batch_size, nproc)
        lo = pos + min(pid * blk, c)
        hi = pos + min(pid * blk + blk, c)
        parts = []
        a = lo
        while a < hi:
            while cur is None or cur[0] + len(cur[1]) <= a:
                cbase, lens_all, rows = next(it)
                cur = (cbase, lens_all, np.cumsum(lens_all != 0), rows)
            cbase, lens_all, vcum, rows = cur
            b = min(hi, cbase + len(lens_all))
            i, j = a - cbase, b - cbase
            vs = int(vcum[i - 1]) if i else 0
            ve = int(vcum[j - 1]) if j else 0
            parts.append((lens_all[i:j], rows[vs:ve]))
            a = b
        yield Block(offset=base_offset + (pos - start), count=c, words=[],
                    prep=PackedSlices(parts, batch_size), padded=(hi == lo))
        pos += c
        if c < gsize:
            return


def skip_stream(words, skip: int):
    """Resume fast-forward: consume up to ``skip`` words; returns how
    many were actually skipped (< ``skip`` on a short stream) — the
    count the client folds into its pass accounting."""
    if skip <= 0:
        return 0
    return sum(1 for _ in itertools.islice(iter(words), skip))
