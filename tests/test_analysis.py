"""dwpa_tpu.analysis: lint rules on seeded violations, the recompilation
sentinel, the cross-layer contract checker, and the full-tree baseline
run (the tier-1 wiring of ``python -m dwpa_tpu.analysis``).

Every lint rule is proven BOTH ways: a seeded violation the pass
demonstrably catches, and the nearest compliant idiom it must stay
silent on — a linter that cries wolf gets baselined into uselessness.
"""

import json
import os
import textwrap

import pytest

import jax
import jax.numpy as jnp

from dwpa_tpu.analysis import (
    RecompilationError, apply_baseline, check_concurrency, check_contracts,
    collect_violations, lint_source, load_baseline, no_recompiles, repo_root,
    run_analysis, watch_compiles, write_baseline,
)
from dwpa_tpu.analysis.baseline import load_whys

OPS_PATH = "dwpa_tpu/ops/seeded.py"
HOT_PATH = "dwpa_tpu/models/m22000.py"


def codes(violations):
    return [v.code for v in violations]


def lint(src, path="dwpa_tpu/somewhere.py"):
    return lint_source(textwrap.dedent(src), path)


# ---------------------------------------------------------------------------
# DW101: python control flow over tracers
# ---------------------------------------------------------------------------


def test_dw101_branch_on_jitted_param():
    vs = lint("""
        import jax

        def step(x):
            if x > 0:
                return x
            return -x

        run = jax.jit(step)
    """)
    assert codes(vs) == ["DW101"]
    assert "branch on a tracer" in vs[0].detail


def test_dw101_loop_and_while_and_ternary():
    vs = lint("""
        import jax

        def step(x, y):
            for v in x:
                y = y + v
            while y:
                y = y - 1
            return y if x else y

        run = jax.jit(step)
    """)
    assert sorted(codes(vs)) == ["DW101", "DW101", "DW101"]


def test_dw101_static_argnames_exempt():
    vs = lint("""
        import jax

        def step(x, mode):
            if mode:
                return x * 2
            return x

        run = jax.jit(step, static_argnames=("mode",))
    """)
    assert vs == []


def test_dw101_static_argnums_exempt():
    vs = lint("""
        import jax

        def step(x, mode):
            if mode:
                return x * 2
            return x

        run = jax.jit(step, static_argnums=(1,))
    """)
    assert vs == []


def test_dw101_shape_len_and_is_none_are_static():
    """Branching on .shape/len()/is-None is decided at trace time —
    the repo's pad/accumulate idioms must stay clean."""
    vs = lint("""
        import jax
        import jax.numpy as jnp

        def step(x, acc):
            if x.shape[0] % 32:
                x = jnp.pad(x, (0, 32 - x.shape[0] % 32))
            acc = x if acc is None else acc + x
            return acc

        run = jax.jit(step)
    """)
    assert vs == []


def test_dw101_taint_through_assignment_and_jnp_calls():
    vs = lint("""
        import jax
        import jax.numpy as jnp

        def step(x):
            total = jnp.sum(x)
            if total > 3:
                return x
            return -x

        run = jax.jit(step)
    """)
    assert codes(vs) == ["DW101"]


def test_dw101_lambda_passed_to_entrypoint():
    vs = lint("""
        import jax

        out = jax.vmap(lambda row: row if row else -row)(rows)
    """)
    assert codes(vs) == ["DW101"]


def test_dw101_repo_shard_wrapper_counts_as_entrypoint():
    vs = lint("""
        def local(batch):
            if batch:
                return batch
            return -batch

        step = _shard(mesh, local, in_specs, out_specs)
    """)
    assert codes(vs) == ["DW101"]


def test_dw104_concretizing_call_in_trace():
    vs = lint("""
        import jax

        def step(x):
            return float(x)

        run = jax.jit(step)
    """)
    assert codes(vs) == ["DW104"]


# ---------------------------------------------------------------------------
# DW102: uncached jit
# ---------------------------------------------------------------------------


def test_dw102_immediate_invoke():
    vs = lint("""
        import jax

        def crack(x):
            return jax.jit(lambda a: a * 2)(x)
    """)
    assert "DW102" in codes(vs)
    assert "fresh compile cache" in vs[0].detail


def test_dw102_jit_in_loop_uncached():
    vs = lint("""
        import jax

        def sweep(batches):
            outs = []
            for b in batches:
                f = jax.jit(kernel)
                outs.append(f(b))
            return outs
    """)
    assert "DW102" in codes(vs)


def test_dw102_cache_store_exempt():
    """The repo's _STEP_CACHE idiom: jit stored under a subscript (or
    attribute) key is a cache, not a leak."""
    vs = lint("""
        import jax

        _CACHE = {}

        def sweep(batches):
            for b in batches:
                if b.key not in _CACHE:
                    _CACHE[b.key] = jax.jit(kernel)
                _CACHE[b.key](b)
    """)
    assert vs == []


# ---------------------------------------------------------------------------
# DW103: ops/ dtype lattice
# ---------------------------------------------------------------------------


def test_dw103_float_dtype_in_ops():
    src = """
        import jax.numpy as jnp

        def mix(x):
            return x.astype(jnp.float32)
    """
    assert codes(lint(src, OPS_PATH)) == ["DW103"]
    # same source outside ops/ is out of scope
    assert lint(src, "dwpa_tpu/server/core.py") == []


def test_dw103_int64_and_astype_string():
    vs = lint("""
        import numpy as np

        def widen(x):
            y = np.int64(3)
            return x.astype("float64")
    """, OPS_PATH)
    assert sorted(codes(vs)) == ["DW103", "DW103"]


def test_dw103_lattice_dtypes_clean():
    vs = lint("""
        import jax.numpy as jnp
        import numpy as np

        def ok(x):
            a = jnp.uint32(7)
            b = np.uint8(1)
            return x.astype(jnp.int32) + a + b
    """, OPS_PATH)
    assert vs == []


# ---------------------------------------------------------------------------
# DW104: host syncs in hot-path modules
# ---------------------------------------------------------------------------


def test_dw104_item_and_bare_asarray_in_hot_path():
    src = """
        import numpy as np

        def gate(hits_dev, found_dev):
            if int(np.asarray(hits_dev).sum()) == 0:
                return None
            return hits_dev.item()
    """
    vs = lint(src, HOT_PATH)
    assert sorted(codes(vs)) == ["DW104", "DW104"]
    # out of the hot-path scope: silent
    assert lint(src, "dwpa_tpu/server/core.py") == []


def test_dw104_dtype_kwarg_marks_host_packing():
    vs = lint("""
        import numpy as np

        def pack(words):
            return np.asarray(words, dtype=np.uint32)
    """, HOT_PATH)
    assert vs == []


# ---------------------------------------------------------------------------
# DW105: bench timed sections
# ---------------------------------------------------------------------------


def test_dw105_unsynced_timed_section():
    vs = lint("""
        import time
        import jax.numpy as jnp

        def bench(x):
            t0 = time.perf_counter()
            y = jnp.dot(x, x)
            dt = time.perf_counter() - t0
            return y, dt
    """, "bench.py")
    assert codes(vs) == ["DW105"]
    assert "never forces completion" in vs[0].detail


def test_dw105_synced_sections_clean():
    vs = lint("""
        import time
        import numpy as np
        import jax
        import jax.numpy as jnp

        def bench_blocked(x):
            t0 = time.perf_counter()
            y = jax.block_until_ready(jnp.dot(x, x))
            return time.perf_counter() - t0

        def bench_fetched(x):
            t0 = time.perf_counter()
            y = np.asarray(jnp.dot(x, x))
            return time.perf_counter() - t0

        def bench_engine(engine, words):
            t0 = time.perf_counter()
            engine.crack(words)
            return time.perf_counter() - t0

        def bench_hostwork(words):
            t0 = time.perf_counter()
            n = sum(len(w) for w in words)
            return time.perf_counter() - t0
    """, "bench.py")
    assert vs == []


def test_dw105_scoped_to_bench_files():
    vs = lint("""
        import time
        import jax.numpy as jnp

        def helper(x):
            t0 = time.perf_counter()
            y = jnp.dot(x, x)
            return y, time.perf_counter() - t0
    """, "dwpa_tpu/utils/bytesops.py")
    assert vs == []


# ---------------------------------------------------------------------------
# DW106: telemetry discipline (obs spans + metric emission)
# ---------------------------------------------------------------------------


def test_dw106_emission_inside_traced_function():
    vs = lint("""
        import jax

        def step(x, counter):
            counter.inc()
            return x * 2

        run = jax.jit(step)
    """)
    assert codes(vs) == ["DW106"]
    assert "host-side" in vs[0].detail


def test_dw106_at_update_is_not_emission():
    """jnp's functional update x.at[i].set(v) shares the .set name with
    the gauge API; it is array code and must stay clean."""
    vs = lint("""
        import jax

        def step(x):
            return x.at[0].set(1)

        run = jax.jit(step)
    """)
    assert vs == []


def test_dw106_unsynced_with_span():
    src = """
        import jax.numpy as jnp

        def bench(x, tracer):
            with tracer.span("hot") as sp:
                y = jnp.dot(x, x)
            return y, sp.seconds
    """
    vs = lint(src, "bench.py")
    assert codes(vs) == ["DW106"]
    assert "never forces completion" in vs[0].detail
    # span-sync is scoped to the instrumented files
    assert lint(src, "dwpa_tpu/server/core.py") == []


def test_dw106_synced_spans_clean():
    """The three compliant idioms: engine crack* (syncs internally), an
    explicit np.asarray fetch, and the API's sync= kwarg."""
    vs = lint("""
        import numpy as np
        import jax.numpy as jnp

        def bench_crack(engine, words, tracer):
            with tracer.span("crack") as sp:
                engine.crack(words)
            return sp.seconds

        def bench_fetch(x, tracer):
            with tracer.span("dot") as sp:
                y = np.asarray(jnp.dot(x, x))
            return sp.seconds

        def bench_kw(x, tracer, y):
            with tracer.span("dot", sync=lambda: y):
                y = jnp.dot(x, x)
    """, "bench.py")
    assert vs == []


def test_dw106_start_stop_pair():
    vs = lint("""
        import jax.numpy as jnp

        def bench_pair(x, tracer):
            sp = tracer.start("hot")
            y = jnp.dot(x, x)
            sp.stop()
            return y

        def bench_pair_ok(engine, words, tracer):
            sp = tracer.start("hot")
            engine.crack_batch(words)
            sp.stop()
            return sp.seconds

        def thread_lifecycle_ok(t):
            t.start()
            t.stop()
    """, "bench.py")
    assert codes(vs) == ["DW106"]


# ---------------------------------------------------------------------------
# DW107: candidate-feed thread discipline
# ---------------------------------------------------------------------------


def test_dw107_blocking_queue_get_in_traced_region():
    vs = lint("""
        import jax

        def step(x, in_queue):
            v = in_queue.get()
            return x + v

        run = jax.jit(step)
    """)
    assert codes(vs) == ["DW107"]
    assert "blocking" in vs[0].detail and "in_queue" in vs[0].detail


def test_dw107_lock_and_event_waits_in_traced_region():
    vs = lint("""
        import jax

        def step(x, self):
            self._lock.acquire()
            self._done_event.wait()
            return x

        run = jax.jit(step)
    """)
    assert codes(vs) == ["DW107", "DW107"]


def test_dw107_nonblocking_gets_and_joins_stay_clean():
    """dict .get, str .join and os.path.join share method names with
    the blocking primitives; the receiver heuristic must not flag
    them — a linter that cries wolf gets baselined into uselessness."""
    vs = lint("""
        import os
        import jax

        def step(x, cfg, parts):
            k = cfg.get("scale", 1)
            name = "-".join(["a", "b"])
            p = os.path.join("a", "b")
            return x * k

        run = jax.jit(step)
    """)
    assert vs == []


def test_dw107_blocking_get_outside_trace_is_fine():
    vs = lint("""
        def pump(in_queue):
            return in_queue.get()
    """)
    assert vs == []


def test_dw107_feed_producer_device_api():
    src = """
        import jax
        import jax.numpy as jnp

        class F:
            def _produce(self):
                y = jnp.zeros((4,))
                return jax.device_put(y)
    """
    # jnp.zeros is flagged; the allowed H2D staging call is not
    vs = lint(src, "dwpa_tpu/feed/seeded.py")
    assert codes(vs) == ["DW107"]
    assert "producer" in vs[0].detail and "zeros" in vs[0].detail
    # scoped to dwpa_tpu/feed/: the same source elsewhere is clean
    assert lint(src, "dwpa_tpu/server/core.py") == []


def test_dw107_feed_producer_pure_host_work_clean():
    vs = lint("""
        import numpy as np

        class F:
            def _produce(self):
                rows = np.zeros((4, 16), np.uint32)
                self._pack(rows)
                return rows

            def consume(self):
                import jax.numpy as jnp
                return jnp.asarray(self._buf)  # consumer side: allowed
    """, "dwpa_tpu/feed/seeded.py")
    assert vs == []


def test_dw107_real_feed_tree_is_clean():
    """The shipped feed subsystem obeys its own discipline."""
    from dwpa_tpu.analysis.linter import lint_file

    root = repo_root()
    for mod in ("__init__", "framing", "pipeline", "staging", "dictcache"):
        path = os.path.join(root, "dwpa_tpu", "feed", mod + ".py")
        assert [v for v in lint_file(path, root)
                if v.code == "DW107"] == [], mod


# ---------------------------------------------------------------------------
# DW108: PMK-store discipline
# ---------------------------------------------------------------------------


def test_dw108_store_lookup_in_traced_region():
    vs = lint("""
        import jax

        def step(x, pmk_store):
            pmks = pmk_store.lookup(b"essid", x)
            return x

        run = jax.jit(step)
    """)
    assert codes(vs) == ["DW108"]
    assert "host mmap/dict work" in vs[0].detail


def test_dw108_mmap_in_traced_region():
    vs = lint("""
        import jax
        import mmap

        def step(x, f):
            mm = mmap.mmap(f.fileno(), 0)
            return x

        run = jax.jit(step)
    """)
    assert codes(vs) == ["DW108"]


def test_dw108_host_side_store_io_clean():
    """The shipped idiom — producer-thread lookups, consumer-thread
    write-back — is host code outside any trace and must stay clean."""
    vs = lint("""
        def split(pmk_store, essid, words):
            return pmk_store.lookup(essid, words)
    """)
    assert vs == []
    # dict/config .lookup on a non-store receiver never flags
    vs = lint("""
        import jax

        def step(x, table):
            k = table.lookup
            return x

        run = jax.jit(step)
    """)
    assert vs == []


def test_dw108_writeback_outside_consumer_set():
    """A store .put from a feed producer (or anywhere outside the
    allowed set) is a write-back from the wrong thread; the engine's
    post-fetch seam and the store's own internals stay clean."""
    src = """
        class F:
            def _produce(self):
                self._pmk_store.put(b"e", self.words, self.pmks)
    """
    vs = lint(src, "dwpa_tpu/feed/seeded.py")
    assert codes(vs) == ["DW108"]
    assert "consumer-thread" in vs[0].detail
    assert lint(src, "dwpa_tpu/models/m22000.py") == []
    assert lint(src, "dwpa_tpu/pmkstore/store.py") == []


def test_dw108_queue_put_is_not_writeback():
    """queue.put shares the method name; the receiver heuristic keeps
    the feed's real queue traffic out of DW108."""
    vs = lint("""
        def pump(out_queue, x):
            out_queue.put(x)
    """, "dwpa_tpu/feed/seeded.py")
    assert vs == []


def test_dw108_real_pmkstore_tree_is_clean():
    """The shipped store/stage/engine wiring obeys its own discipline."""
    from dwpa_tpu.analysis.linter import lint_file

    root = repo_root()
    for rel in ("dwpa_tpu/pmkstore/store.py", "dwpa_tpu/pmkstore/stage.py",
                "dwpa_tpu/pmkstore/__init__.py", "dwpa_tpu/feed/pipeline.py",
                "dwpa_tpu/client/main.py"):
        path = os.path.join(root, *rel.split("/"))
        assert [v for v in lint_file(path, root)
                if v.code == "DW108"] == [], rel


# ---------------------------------------------------------------------------
# DW111: packed-dict-cache discipline
# ---------------------------------------------------------------------------


def test_dw111_cache_read_in_traced_region():
    vs = lint("""
        import jax

        def step(x, dict_cache):
            rd = dict_cache.reader("0" * 32)
            return x

        run = jax.jit(step)
    """, "dwpa_tpu/feed/seeded.py")
    assert codes(vs) == ["DW111"]
    assert "producer-thread host work" in vs[0].detail


def test_dw111_cache_io_outside_feed_subsystem():
    """Cache I/O from client/engine code is the wrong seam — the same
    source is clean when it lives under dwpa_tpu/feed/."""
    src = """
        def warm(self, dhash):
            return self.dict_cache.reader(dhash)
    """
    vs = lint(src, "dwpa_tpu/models/seeded.py")
    assert codes(vs) == ["DW111"]
    assert "feed producer threads" in vs[0].detail
    assert lint(src, "dwpa_tpu/feed/seeded.py") == []


def test_dw111_non_cache_receivers_stay_clean():
    """csv.writer / conn.commit / q.abort share method names with the
    cache API; the receiver heuristic keeps them out of DW111."""
    vs = lint("""
        def host_work(csv, conn, q, f):
            w = csv.writer(f)
            conn.commit()
            q.abort()
    """, "dwpa_tpu/client/seeded.py")
    assert vs == []


def test_dw111_holding_a_handle_is_not_io():
    """The client CONSTRUCTS the cache and passes it into the feed —
    only I/O methods flag, not construction or attribute access."""
    vs = lint("""
        from ..feed.dictcache import DictCache

        def setup(cfg, registry):
            cache = DictCache(cfg.dict_cache_dir, registry=registry)
            return cache.root
    """, "dwpa_tpu/client/seeded.py")
    assert vs == []


def test_dw111_real_tree_is_clean():
    """The shipped dictcache/feed/client wiring obeys its own seam."""
    from dwpa_tpu.analysis.linter import lint_file

    root = repo_root()
    for rel in ("dwpa_tpu/feed/dictcache.py", "dwpa_tpu/feed/pipeline.py",
                "dwpa_tpu/feed/framing.py", "dwpa_tpu/feed/__init__.py",
                "dwpa_tpu/client/main.py", "dwpa_tpu/models/m22000.py"):
        path = os.path.join(root, *rel.split("/"))
        assert [v for v in lint_file(path, root)
                if v.code == "DW111"] == [], rel


# ---------------------------------------------------------------------------
# DW112: client transport confinement
# ---------------------------------------------------------------------------


def test_dw112_flags_raw_transport_in_client():
    src = """
        import time
        import urllib.request

        def nap_and_poll(url):
            time.sleep(5)
            return urllib.request.urlopen(url).read()
    """
    vs = lint(src, "dwpa_tpu/client/watchdog.py")
    assert codes(vs) == ["DW112", "DW112"]
    assert "urllib" in vs[0].detail and "time.sleep" in vs[1].detail
    # protocol.py IS the transport seam; outside the client package the
    # rule does not apply at all
    assert lint(src, "dwpa_tpu/client/protocol.py") == []
    assert lint(src, "dwpa_tpu/server/core.py") == []


def test_dw112_flags_from_imports():
    assert codes(lint("""
        from urllib.request import urlopen

        def poll(url):
            return urlopen(url).read()
    """, "dwpa_tpu/client/main.py")) == ["DW112"]
    assert codes(lint("""
        from time import sleep

        def nap():
            sleep(2)
    """, "dwpa_tpu/client/main.py")) == ["DW112"]


def test_dw112_allows_injected_sleep_and_perf_counter():
    """The sanctioned idioms stay clean: the injected api.sleep (however
    the api object is reached) and time's non-blocking clock calls."""
    assert lint("""
        import time

        def loop(self):
            t0 = time.perf_counter()
            self.api.sleep(self.api.backoff)
            api = self.api
            api.sleep(1.0)
            return time.perf_counter() - t0
    """, "dwpa_tpu/client/main.py") == []


def test_dw112_real_tree_is_clean():
    """The shipped client package obeys its own transport seam."""
    from dwpa_tpu.analysis.linter import lint_file

    root = repo_root()
    client_dir = os.path.join(root, "dwpa_tpu", "client")
    for name in sorted(os.listdir(client_dir)):
        if not name.endswith(".py"):
            continue
        path = os.path.join(client_dir, name)
        assert [v for v in lint_file(path, root)
                if v.code == "DW112"] == [], name


# ---------------------------------------------------------------------------
# DW113: no host rule expansion on the mesh-aggregate feed path
# ---------------------------------------------------------------------------

STREAMS_PATH = "dwpa_tpu/parallel/streams.py"


def test_dw113_flags_apply_rules_in_streams():
    """The seeded failure mode: a stream 'helpfully' expanding its base
    block through the host interpreter before dispatch — exactly the
    serialization the device-expansion seam removed."""
    src = """
        from ..rules import apply_rules

        def _prepare_block(self, block):
            return list(apply_rules(self.rules, iter(block.words)))
    """
    vs = lint(src, STREAMS_PATH)
    assert codes(vs) == ["DW113", "DW113"]
    assert "base-word blocks" in vs[0].detail
    assert "build_rules_step" in vs[1].detail
    # the engine's own host tail (models/) is outside the scope, as is
    # arbitrary host-side code
    assert lint(src, "dwpa_tpu/models/m22000.py") == []
    assert lint(src, "dwpa_tpu/server/core.py") == []


def test_dw113_flags_rule_apply_in_feed_producer():
    vs = lint("""
        def _produce_expanded(rules, words):
            for w in words:
                for rr in rules:
                    out = rr.apply(w)
                    if out is not None:
                        yield out
    """, "dwpa_tpu/feed/pipeline.py")
    assert codes(vs) == ["DW113"]
    assert "purge/overflow tail" in vs[0].detail


def test_dw113_non_rule_apply_receivers_stay_clean():
    """.apply() on non-rule receivers (a thread pool, a dataframe) and
    rule handling WITHOUT interpretation (splitting, packing, counting)
    are the compliant idioms."""
    assert lint("""
        def _produce(pool, frame, rules):
            pool.apply(len, (rules,))
            frame.apply(str)
            eligible = [r for r in rules if r.steps is not None]
            return len(eligible)
    """, "dwpa_tpu/feed/dictcache.py") == []


def test_dw113_real_stream_and_feed_tree_is_clean():
    """The shipped mesh-aggregate path obeys its own seam: streams and
    the feed subsystem never host-interpret a rule."""
    from dwpa_tpu.analysis.linter import lint_file

    root = repo_root()
    targets = [os.path.join(root, "dwpa_tpu", "parallel", "streams.py")]
    feed_dir = os.path.join(root, "dwpa_tpu", "feed")
    targets += [os.path.join(feed_dir, n) for n in sorted(os.listdir(feed_dir))
                if n.endswith(".py")]
    for path in targets:
        assert [v for v in lint_file(path, root)
                if v.code == "DW113"] == [], path


# ---------------------------------------------------------------------------
# DW116: framed-mask dispatch seam
# ---------------------------------------------------------------------------


def test_dw116_flags_raw_enumerator_on_dispatch_path():
    """The seeded failure mode: the client crack loop 'helpfully'
    enumerating a mask shard host-side — re-deriving the framing by
    hand and shipping candidate bytes the device generator exists to
    absorb."""
    src = """
        from ..gen.mask import mask_words

        def _run_shard(self, shard):
            for w in mask_words(shard["mask"], skip=shard["skip"]):
                self._feed(w)
    """
    vs = lint(src, "dwpa_tpu/client/main.py")
    assert codes(vs) == ["DW116", "DW116"]
    assert "mask_blocks" in vs[0].detail
    assert "_prepare_block" in vs[1].detail
    # the engine's device-generation seam and the low-volume targeted
    # host generators are outside the scope by design
    assert lint(src, "dwpa_tpu/models/m22000.py") == []
    assert lint(src, "dwpa_tpu/client/targeted.py") == []


def test_dw116_flags_hand_built_maskprep_in_streams():
    """A hand-built MaskPrep carries whatever start offset the caller
    typed — off mask_blocks' keyspace-bounded framing, resume offsets
    drift off hashcat -s coordinates."""
    src = """
        from ..gen.mask import MaskPrep

        def _requeue(self, block):
            return MaskPrep(block.prep.mask, block.prep.custom, 0)
    """
    vs = lint(src, "dwpa_tpu/parallel/streams.py")
    assert codes(vs) == ["DW116", "DW116"]
    assert "hashcat -s" in vs[1].detail


def test_dw116_flags_device_enumerator_in_feed_and_sched():
    src = """
        def _produce_mask(self, mask, start, batch):
            from ..gen.mask import device_mask_words
            return device_mask_words(mask, start, batch)
    """
    for path in ("dwpa_tpu/feed/pipeline.py", "dwpa_tpu/sched/fuse.py",
                 "dwpa_tpu/keyspace/schedule.py"):
        vs = lint(src, path)
        assert codes(vs) == ["DW116", "DW116"], path


def test_dw116_mask_blocks_is_the_sanctioned_carrier():
    """The compliant idiom: frame the shard through mask_blocks and hand
    the framed blocks to the engine — exactly what the client's mask
    pass does."""
    assert lint("""
        from ..gen.mask import mask_blocks

        def _run_shard(self, engine, shard):
            blocks = mask_blocks(shard["mask"], 4096, skip=shard["skip"],
                                 limit=shard["limit"])
            self._crack_blocks(engine, blocks, on_batch=None)
    """, "dwpa_tpu/client/main.py") == []


def test_dw116_real_dispatch_tree_is_clean():
    """The shipped mask path obeys its own seam: streams, feed, the
    client crack loop and the scheduling layers never enumerate raw."""
    from dwpa_tpu.analysis.linter import lint_file

    root = repo_root()
    targets = [os.path.join(root, "dwpa_tpu", "parallel", "streams.py"),
               os.path.join(root, "dwpa_tpu", "client", "main.py")]
    for sub in (("feed",), ("sched",), ("keyspace",)):
        d = os.path.join(root, "dwpa_tpu", *sub)
        targets += [os.path.join(d, n) for n in sorted(os.listdir(d))
                    if n.endswith(".py")]
    for path in targets:
        assert [v for v in lint_file(path, root)
                if v.code == "DW116"] == [], path


# ---------------------------------------------------------------------------
# DW109: fused-pad-width discipline
# ---------------------------------------------------------------------------

FUSE_PATH = "dwpa_tpu/sched/fuse.py"


def test_dw109_data_dependent_pad_width():
    """The seeded failure mode: padding the per-lane row buffer to the
    candidate COUNT instead of the static table — every unit mix would
    retrace the fused PMK step."""
    src = """
        import numpy as np

        def pack(parts, batch, n):
            total = sum(len(w) for _, w in parts)
            rows = np.zeros((total, 16), np.uint32)
            return rows
    """
    vs = lint(src, FUSE_PATH)
    assert codes(vs) == ["DW109"]
    assert "static fused-width pad table" in vs[0].detail
    # scoped to the fused-batch packers: elsewhere the same source is clean
    assert lint(src, "dwpa_tpu/server/core.py") == []


def test_dw109_arithmetic_on_count_and_empty_flag():
    vs = lint("""
        import numpy as np

        def pack(nmiss, n):
            W = -(-nmiss // n) * n
            rows = np.empty((W, 16), dtype=np.uint32)
            return rows
    """, "dwpa_tpu/pmkstore/stage.py")
    assert codes(vs) == ["DW109"]


def test_dw109_table_widths_clean():
    """Every accepted width shape at once: the table call, a subscript
    of the table, a conditional over accepted branches, and a name whose
    assignments all resolve to the table."""
    vs = lint("""
        import numpy as np

        def pack(parts, batch, n, total, nmiss, all_miss):
            W = fused_width(batch, n, total)
            rows = np.zeros((W, 16), np.uint32)
            Wm = W if all_miss else fused_width(batch, n, max(nmiss, 1))
            miss_rows = np.zeros((Wm, 16), np.uint32)
            smallest = fused_widths(batch, n)[0]
            probe = np.zeros((smallest, 16), np.uint32)
            fixed = np.zeros((8, 16), np.uint32)
            return rows, miss_rows, probe, fixed
    """, FUSE_PATH)
    assert vs == []


def test_dw109_non_row_buffers_out_of_scope():
    """Only [W, 16] row buffers are policed — 1-D lane vectors and
    other-width allocations are not pmk_kernel inputs."""
    vs = lint("""
        import numpy as np

        def pack(total):
            unit_id = np.zeros(total, np.int32)
            lens = np.zeros((total,), np.uint8)
            pmks = np.zeros((8, total), np.uint32)
            return unit_id, lens, pmks
    """, FUSE_PATH)
    assert vs == []


def test_dw109_real_fused_packers_are_clean():
    """The shipped packers obey their own discipline — proven against
    the real tree, not a fixture."""
    from dwpa_tpu.analysis.linter import lint_file

    root = repo_root()
    for rel in ("dwpa_tpu/sched/fuse.py", "dwpa_tpu/pmkstore/stage.py"):
        path = os.path.join(root, *rel.split("/"))
        assert [v for v in lint_file(path, root)
                if v.code == "DW109"] == [], rel


# ---------------------------------------------------------------------------
# DW110: device-stream isolation
# ---------------------------------------------------------------------------

STREAMS_PATH = "dwpa_tpu/parallel/streams.py"


def test_dw110_collective_in_stream_module():
    """The seeded failure mode: a psum hits-gate copied from the
    lockstep step into a stream — it would barrier every stream
    against its siblings (or deadlock on uneven block counts)."""
    src = """
        import jax

        def gate(found):
            import jax.numpy as jnp
            return jax.lax.psum(jnp.sum(found), "dp")
    """
    vs = lint(src, STREAMS_PATH)
    assert codes(vs) == ["DW110"]
    assert "collective" in vs[0].detail
    # scoped to the stream modules: the lockstep step keeps its psum
    assert lint(src, "dwpa_tpu/parallel/step.py") == []


def test_dw110_blocking_fetch_in_dispatch_loop():
    vs = lint("""
        import jax

        def run(blocks, step):
            outs = []
            for b in blocks:
                outs.append(jax.device_get(step(b)))
            while outs:
                outs.pop().block_until_ready()
    """, STREAMS_PATH)
    assert codes(vs) == ["DW110", "DW110"]
    assert all("stream loop" in v.detail for v in vs)


def test_dw110_bare_device_put():
    vs = lint("""
        import jax

        def stage(x):
            return jax.device_put(x)
    """, STREAMS_PATH)
    assert codes(vs) == ["DW110"]
    assert "explicit device/sharding" in vs[0].detail


def test_dw110_compliant_stream_idioms_clean():
    """The nearest compliant shapes: an explicitly-placed device_put
    (positional and keyword), a fetch OUTSIDE any loop (the engine's
    post-loop decode), and the engine's _collect call inside the loop
    (the one allowed sync, a method of the engine — not a raw fetch)."""
    vs = lint("""
        import jax

        def stage(x, dev, sharding):
            a = jax.device_put(x, dev)
            b = jax.device_put(x, device=dev)
            c = jax.device_put(x, sharding=sharding)
            return a, b, c

        def run(eng, blocks):
            founds = []
            for b in blocks:
                founds.extend(eng._collect(eng._dispatch(b)))
            return jax.device_get(founds)
    """, STREAMS_PATH)
    assert vs == []


def test_dw110_real_stream_module_is_clean():
    """The shipped stream executor obeys its own discipline — proven
    against the real tree, not a fixture."""
    from dwpa_tpu.analysis.linter import lint_file

    root = repo_root()
    path = os.path.join(root, *STREAMS_PATH.split("/"))
    assert [v for v in lint_file(path, root)
            if v.code == "DW110"] == []


# ---------------------------------------------------------------------------
# recompilation sentinel
# ---------------------------------------------------------------------------


def test_watch_compiles_counts_misses_and_hits():
    f = jax.jit(lambda a: a * 2 + 1)
    x = jnp.arange(16.0)  # built OUTSIDE the guard: its iota is not f's
    with watch_compiles() as warm:
        f(x)
    assert warm.count == 1 and warm.names

    with watch_compiles() as steady:
        for _ in range(3):
            f(x)  # same shape: jit cache hits
    assert steady.count == 0


def test_no_recompiles_catches_per_batch_compile():
    """The seeded failure mode: a fresh jit per batch (or a shape leak)
    recompiles every iteration of a sweep."""
    with pytest.raises(RecompilationError, match="recompiling the hot"):
        with no_recompiles(label="seeded sweep"):
            for n in (4, 5, 6):
                jax.jit(lambda a: a + 1)(jnp.arange(float(n)))


def test_no_recompiles_budget_allows_warmup():
    f = jax.jit(lambda a: a - 3)
    x = jnp.arange(32.0)
    with no_recompiles(allowed=1, label="first-shape budget"):
        f(x)                      # one intentional compile
        f(x)                      # steady


def test_recompile_sentinel_fixture(recompile_sentinel):
    f = jax.jit(lambda a: a * a)
    x = jnp.arange(8.0)
    f(x)  # warmup outside the guard
    with recompile_sentinel(allowed=0, label="fixture sweep"):
        for _ in range(4):
            f(x)
    with pytest.raises(RecompilationError):
        with recompile_sentinel(label="fixture leak"):
            jax.jit(lambda a: a * a + 0.5)(x)


def test_engine_batch_sweep_stays_compiled(recompile_sentinel):
    """The client-sweep wiring the sentinel exists for: after warmup, a
    steady run of same-shape engine batches must not touch XLA — one
    per-batch recompile here is the throughput collapse DW102 describes
    statically."""
    from dwpa_tpu import testing as synth
    from dwpa_tpu.models.m22000 import M22000Engine

    eng = M22000Engine(
        [synth.make_pmkid_line(b"sentinel-psk", b"SentinelNet", seed="sn1")],
        batch_size=64,
    )
    eng.crack_batch([b"warm-%04d" % i for i in range(64)])
    with recompile_sentinel(allowed=0, label="engine batch sweep"):
        for rep in range(3):
            eng.crack_batch([b"sweep%d-%04d" % (rep, i) for i in range(64)])


# ---------------------------------------------------------------------------
# contract checker
# ---------------------------------------------------------------------------


_GOOD_TREE = {
    "dwpa_tpu/client/protocol.py": """
        def get_work(self, dictcount):
            work = self.fetch({"dictcount": dictcount})
            for field in ("hkey", "dicts", "hashes"):
                if field not in work:
                    raise ValueError(field)
            return work

        def put_work(self, hkey, candidates):
            return self.fetch({"hkey": hkey, "type": "bssid",
                               "cand": candidates})
    """,
    "dwpa_tpu/client/main.py": """
        def process(self, work):
            for d in work.get("dicts", []):
                self.download(d["dpath"], d["dhash"])
            work["_progress"] = 1
            cand = [{"k": "aa", "v": "bb"}]
            return work["hkey"], work.get("rules"), cand
    """,
    "dwpa_tpu/server/core.py": """
        def get_work(self, dictcount):
            dicts = self.db.q("SELECT * FROM dicts")
            work = {
                "hkey": "h",
                "dicts": [{"dhash": d["dhash"], "dpath": d["dpath"]}
                          for d in dicts],
                "hashes": [],
            }
            work["rules"] = "r"
            return work

        def put_work(self, data):
            cands = data.get("cand") or []
            for pair in cands:
                k, v = pair.get("k"), pair.get("v")
            return data.get("hkey"), data.get("type"), data.get("ip")
    """,
    "dwpa_tpu/server/api.py": """
        def route(core, data, environ):
            data.setdefault("ip", environ.get("REMOTE_ADDR", ""))
            return core.put_work(data)
    """,
    "dwpa_tpu/server/db.py": '''
        SCHEMA = """
        CREATE TABLE dicts (
            d_id INTEGER PRIMARY KEY,
            dpath TEXT, dname TEXT, dhash TEXT, rules TEXT, wcount INTEGER
        );
        CREATE TABLE nets (net_id INTEGER PRIMARY KEY, ssid BLOB);
        """

        def add_dict(db):
            db.x("INSERT INTO dicts(dpath, dname, dhash) VALUES (?,?,?)")
    ''',
}


def _write_tree(tmp_path, overrides=None):
    files = dict(_GOOD_TREE, **(overrides or {}))
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return str(tmp_path)


def test_contracts_clean_tree(tmp_path):
    assert check_contracts(_write_tree(tmp_path)) == []


def test_contracts_dw201_client_reads_unemitted_field(tmp_path):
    root = _write_tree(tmp_path, {"dwpa_tpu/client/main.py": """
        def process(self, work):
            return work["hkey"], work["wordlist_url"]
    """})
    vs = check_contracts(root)
    assert [v.code for v in vs] == ["DW201"]
    assert "wordlist_url" in vs[0].detail


def test_contracts_dw201_underscore_keys_are_client_local(tmp_path):
    root = _write_tree(tmp_path, {"dwpa_tpu/client/main.py": """
        def process(self, work):
            return work["hkey"], work.get("_progress"), work["_ver"]
    """})
    assert check_contracts(root) == []


def test_contracts_dw202_dict_entry_drift(tmp_path):
    root = _write_tree(tmp_path, {"dwpa_tpu/client/main.py": """
        def process(self, work):
            for d in work.get("dicts", []):
                self.download(d["dpath"], d["dsize"])
            return work["hkey"]
    """})
    vs = check_contracts(root)
    assert [v.code for v in vs] == ["DW202"]
    assert "dsize" in vs[0].detail


def test_contracts_dw202_server_entry_key_not_a_column(tmp_path):
    bad_core = _GOOD_TREE["dwpa_tpu/server/core.py"].replace(
        '"dhash": d["dhash"]', '"dgest": d["dhash"]')
    root = _write_tree(tmp_path, {"dwpa_tpu/server/core.py": bad_core})
    vs = check_contracts(root)
    # two sightings of the same drift: the client reads "dhash" which the
    # server no longer emits, and "dgest" matches no dicts column
    assert codes(vs) == ["DW202", "DW202"]
    assert any("dgest" in v.detail for v in vs)


def test_contracts_dw203_server_reads_unsent_field(tmp_path):
    bad_core = _GOOD_TREE["dwpa_tpu/server/core.py"].replace(
        'data.get("type")', 'data.get("claim_type")')
    root = _write_tree(tmp_path, {"dwpa_tpu/server/core.py": bad_core})
    vs = check_contracts(root)
    assert [v.code for v in vs] == ["DW203"]
    assert "claim_type" in vs[0].detail


def test_contracts_dw204_insert_unknown_column(tmp_path):
    bad_db = _GOOD_TREE["dwpa_tpu/server/db.py"].replace(
        "INSERT INTO dicts(dpath, dname, dhash)",
        "INSERT INTO dicts(dpath, dname, digest)")
    root = _write_tree(tmp_path, {"dwpa_tpu/server/db.py": bad_db})
    vs = check_contracts(root)
    assert [v.code for v in vs] == ["DW204"]
    assert "digest" in vs[0].detail


def test_contracts_real_tree_is_clean():
    """The shipped client/server/schema agree — this is the check that
    catches protocol drift at test time, not in production."""
    assert check_contracts(repo_root()) == []


# ---------------------------------------------------------------------------
# baseline mechanics + the tier-1 full-tree run
# ---------------------------------------------------------------------------


def _viol(code="DW104", path="a.py", snippet="x = 1", line=3):
    from dwpa_tpu.analysis.linter import Violation

    return Violation(code, path, line, "msg", snippet)


def test_baseline_absorbs_by_fingerprint_not_line():
    base = {(v.code, v.path, v.snippet): 1 for v in [_viol(line=3)]}
    new, absorbed, stale = apply_baseline([_viol(line=99)], base)
    assert new == [] and len(absorbed) == 1 and stale == []


def test_baseline_multiplicity_and_new_and_stale():
    base = {("DW104", "a.py", "x = 1"): 2}
    vs = [_viol(), _viol(), _viol(),             # 3 occurrences, budget 2
          _viol(code="DW103", snippet="y = 2")]  # not baselined
    new, absorbed, stale = apply_baseline(vs, base)
    assert len(absorbed) == 2
    assert sorted(v.code for v in new) == ["DW103", "DW104"]
    assert stale == []
    # all fixed -> entry reported stale, nothing fails
    new2, absorbed2, stale2 = apply_baseline([], base)
    assert new2 == [] and stale2 == [("DW104", "a.py", "x = 1")]


def test_baseline_write_load_round_trip(tmp_path):
    path = str(tmp_path / "baseline.json")
    write_baseline([_viol(), _viol(), _viol(code="DW103")], path)
    data = json.loads(open(path).read())
    assert data["version"] == 1
    loaded = load_baseline(path)
    assert loaded[("DW104", "a.py", "x = 1")] == 2
    assert loaded[("DW103", "a.py", "x = 1")] == 1


def test_dw114_flags_untransacted_multi_write():
    """The seeded failure mode: two db.x writes whose combined effect
    the caller assumed atomic — a crash between them tears the ledger."""
    src = """
        def accept(self, net_id):
            self.db.x("UPDATE nets SET n_state = 1 WHERE net_id = ?",
                      (net_id,))
            self.db.x("DELETE FROM n2d WHERE net_id = ?", (net_id,))
    """
    vs = lint(src, "dwpa_tpu/server/core.py")
    assert codes(vs) == ["DW114"]
    assert "Database.tx()" in vs[0].detail
    # out of scope: the same shape outside the server package is clean
    assert lint(src, "dwpa_tpu/client/main.py") == []
    assert lint(src, "bench.py") == []


def test_dw114_tx_wrapped_and_single_site_stay_clean():
    """The compliant idioms: the same sequence under ``with db.tx():``,
    and a SINGLE write site even when looped (per-row autocommit around
    network calls — the geolocate pattern)."""
    assert lint("""
        def accept(self, net_id):
            with self.db.tx():
                self.db.x("UPDATE nets SET n_state = 1 WHERE net_id = ?",
                          (net_id,))
                self.db.x("DELETE FROM n2d WHERE net_id = ?", (net_id,))

        def geolocate(db, rows, lookup):
            for r in rows:
                info = lookup(r)
                db.x("UPDATE bssids SET lat = ? WHERE bssid = ?",
                     (info, r))
    """, "dwpa_tpu/server/jobs.py") == []
    # a bare function using module-level db, two sites -> still flagged
    assert codes(lint("""
        def fixup(db):
            db.x("UPDATE a SET x = 1")
            db.x("UPDATE b SET y = 2")
    """, "dwpa_tpu/server/tools.py")) == ["DW114"]


def test_dw114_nested_scopes_counted_separately():
    """An inner helper's single write must not inflate the enclosing
    function's count: each def is its own atomicity domain."""
    assert lint("""
        def outer(self):
            self.db.x("UPDATE a SET x = 1")

            def inner():
                self.db.x("UPDATE b SET y = 2")
            return inner
    """, "dwpa_tpu/server/core.py") == []


def test_dw114_real_server_tree_is_clean():
    """The refactored server package carries no untransacted
    multi-statement write paths (the PR's whole point)."""
    import os

    root = repo_root()
    server = os.path.join(root, "dwpa_tpu", "server")
    for name in sorted(os.listdir(server)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(server, name), encoding="utf-8") as f:
            vs = lint_source(f.read(), f"dwpa_tpu/server/{name}")
        assert [v for v in vs if v.code == "DW114"] == [], name


# ---------------------------------------------------------------------------
# DW115: server-side scalar candidate verification
# ---------------------------------------------------------------------------


def test_dw115_flags_scalar_verify_loop():
    """The seeded failure mode: one full PBKDF2 per loop iteration on a
    server thread — the shape the precrack verify_batch seam replaces."""
    src = """
        def sweep(h, cands, nc):
            for cand in cands:
                r = oracle.check_key_m22000(h, [cand], nc=nc)
                if r:
                    return r
    """
    vs = lint(src, "dwpa_tpu/server/jobs.py")
    assert codes(vs) == ["DW115"]
    assert "verify_batch" in vs[0].detail
    # out of scope: the sanctioned host-oracle fallback seam, and any
    # non-server path (the client's crack loop batches on device)
    assert lint(src, "dwpa_tpu/server/precrack.py") == []
    assert lint(src, "dwpa_tpu/client/main.py") == []


def test_dw115_batched_and_unlooped_calls_stay_clean():
    """The compliant idioms: the whole candidate list in ONE oracle call
    (keygen_precompute's shape — the oracle scans it internally), and a
    single scalar call outside any loop (a one-claim verify)."""
    assert lint("""
        def keygen(h, cands, nc):
            for _ in range(2):
                keys = [c for _, c in cands]
                r = oracle.check_key_m22000(h, keys, nc=nc)
            return r

        def verify_one(h, psk, nc):
            return oracle.check_key_m22000(h, [psk], nc=nc)
    """, "dwpa_tpu/server/core.py") == []


def test_dw115_nested_loops_flag_each_site_once():
    """A call under two loops is one hazard site, not two (the walk
    visits it from both loop roots; the node set dedups)."""
    vs = lint("""
        def sweep(nets, nc):
            for h in nets:
                while pending(h):
                    r = oracle.check_key_m22000(h, [next_cand(h)], nc=nc)
    """, "dwpa_tpu/server/tools.py")
    assert codes(vs) == ["DW115"]


def test_dw115_real_server_tree_is_clean():
    """The refactored server package routes every candidate sweep
    through verify_batch / the precrack engine (the PR's whole point)."""
    import os

    root = repo_root()
    server = os.path.join(root, "dwpa_tpu", "server")
    for name in sorted(os.listdir(server)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(server, name), encoding="utf-8") as f:
            vs = lint_source(f.read(), f"dwpa_tpu/server/{name}")
        assert [v for v in vs if v.code == "DW115"] == [], name


def test_full_tree_clean_under_checked_in_baseline():
    """The acceptance gate: ``python -m dwpa_tpu.analysis`` exits 0 on
    this tree with the checked-in baseline — every hot-path sync is
    individually accepted, and anything NEW fails tier-1 right here."""
    logs = []
    rc = run_analysis(log=logs.append)
    assert rc == 0, "\n".join(logs)


def test_full_tree_violations_all_known_codes():
    known = {"DW101", "DW102", "DW103", "DW104", "DW105", "DW106", "DW107",
             "DW108", "DW109", "DW111", "DW112", "DW113", "DW114", "DW115",
             "DW116", "DW201", "DW202", "DW203", "DW204", "DW301", "DW302",
             "DW303", "DW304"}
    vs = collect_violations(repo_root())
    assert vs, "the baseline documents accepted syncs; none found?"
    assert {v.code for v in vs} <= known


def test_cli_exits_nonzero_on_new_violation(tmp_path):
    """End-to-end CLI contract on a tree seeded with a fresh violation
    and an empty baseline."""
    from dwpa_tpu.analysis.__main__ import main as cli_main

    root = _write_tree(tmp_path)
    (tmp_path / "dwpa_tpu/ops").mkdir(parents=True, exist_ok=True)
    (tmp_path / "dwpa_tpu/ops/bad.py").write_text(
        "import jax.numpy as jnp\nBAD = jnp.float64\n")
    empty = tmp_path / "empty_baseline.json"
    empty.write_text('{"version": 1, "violations": []}')
    assert cli_main([root, "--baseline", str(empty)]) == 1
    # --update-baseline accepts the tree, after which the run is green
    assert cli_main([root, "--baseline", str(empty),
                     "--update-baseline"]) == 0
    assert cli_main([root, "--baseline", str(empty)]) == 0


# ---------------------------------------------------------------------------
# DW301-DW304: whole-program concurrency analysis
# ---------------------------------------------------------------------------


def _conc_tree(tmp_path, src, rel="dwpa_tpu/svc.py"):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _conc(tmp_path, src, rel="dwpa_tpu/svc.py"):
    return check_concurrency(_conc_tree(tmp_path, src, rel))


def test_dw301_lock_order_inversion(tmp_path):
    vs = _conc(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert codes(vs) == ["DW301"]
    assert "S._a" in vs[0].detail and "S._b" in vs[0].detail


def test_dw301_consistent_order_is_clean(tmp_path):
    assert _conc(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
    """) == []


def test_dw301_inversion_through_a_call(tmp_path):
    """The interprocedural half: no single function inverts, the pair
    of call chains does."""
    vs = _conc(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def take_b(self):
                with self._b:
                    pass

            def one(self):
                with self._a:
                    self.take_b()

            def take_a(self):
                with self._a:
                    pass

            def two(self):
                with self._b:
                    self.take_a()
    """)
    assert codes(vs) == ["DW301"]


def test_dw301_reentrant_nesting_is_not_an_inversion(tmp_path):
    """The core.py accept-path shape: a callee re-enters an RLock its
    caller already holds.  Re-acquisition of a held lock orders
    nothing — flagging it would invert put_work's real hierarchy."""
    assert _conc(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._a = threading.RLock()
                self._b = threading.RLock()

            def inner(self):
                with self._a:      # reentrant: caller holds _a
                    pass

            def outer(self):
                with self._a:
                    with self._b:
                        self.inner()
    """) == []


def test_dw302_unguarded_cross_thread_write(tmp_path):
    vs = _conc(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self.items = []

            def start(self):
                threading.Thread(target=self._worker).start()

            def _worker(self):
                self.items.append(1)

            def add(self, x):
                self.items.append(x)
    """)
    assert codes(vs) == ["DW302"]
    assert "W.items" in vs[0].detail


def test_dw302_common_guard_is_clean(tmp_path):
    assert _conc(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self.items = []
                self._lock = threading.Lock()

            def start(self):
                threading.Thread(target=self._worker).start()

            def _worker(self):
                with self._lock:
                    self.items.append(1)

            def add(self, x):
                with self._lock:
                    self.items.append(x)
    """) == []


def test_dw302_single_thread_writes_are_clean(tmp_path):
    """No spawned root ever writes: confinement needs no lock."""
    assert _conc(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self.items = []

            def add(self, x):
                self.items.append(x)

            def also(self, x):
                self.items.extend(x)
    """) == []


def test_dw302_guard_propagates_through_private_callee(tmp_path):
    """A callee whose every caller holds the lock inherits the guard
    (entry must-hold): the FoundOutbox._append shape."""
    assert _conc(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self.items = []
                self._lock = threading.Lock()

            def start(self):
                threading.Thread(target=self._worker).start()

            def _push(self, x):
                self.items.append(x)

            def _worker(self):
                with self._lock:
                    self._push(1)

            def add(self, x):
                with self._lock:
                    self._push(x)
    """) == []


def test_dw303_blocking_get_while_holding_lock(tmp_path):
    vs = _conc(tmp_path, """
        import queue
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def pump(self):
                with self._lock:
                    return self._q.get()
    """)
    assert codes(vs) == ["DW303"]
    assert "C._lock" in vs[0].detail


def test_dw303_bounded_wait_and_unlocked_get_are_clean(tmp_path):
    assert _conc(tmp_path, """
        import queue
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def bounded(self):
                with self._lock:
                    return self._q.get(timeout=1.0)

            def unlocked(self):
                return self._q.get()
    """) == []


def test_dw303_condition_wait_on_own_lock_is_clean(tmp_path):
    """cv.wait() releases the lock it waits on: holding only the
    condition's own lock is the idiom, not a hazard."""
    assert _conc(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._cv = threading.Condition()

            def park(self):
                with self._cv:
                    self._cv.wait()
    """) == []


def test_dw304_raw_conn_crossing_thread_roots(tmp_path):
    vs = _conc(tmp_path, """
        import threading

        class Core:
            def __init__(self, db):
                self.db = db

            def start(self):
                threading.Thread(target=self._tick).start()

            def _tick(self):
                self._touch()

            def _touch(self):
                self.db.conn.execute("SELECT 1")

            def hits(self):
                self._touch()
    """)
    assert codes(vs) == ["DW304"]
    assert "conn" in vs[0].detail


def test_dw304_funneled_db_api_is_clean(tmp_path):
    assert _conc(tmp_path, """
        import threading

        class Core:
            def __init__(self, db):
                self.db = db

            def start(self):
                threading.Thread(target=self._tick).start()

            def _tick(self):
                self._touch()

            def _touch(self):
                self.db.x("UPDATE nets SET hits = hits + 1")

            def hits(self):
                self._touch()
    """) == []


def test_dw304_single_root_conn_is_clean(tmp_path):
    """A raw handle confined to one thread root stays legal (the db
    module itself, CLI one-shots)."""
    assert _conc(tmp_path, """
        class Tool:
            def __init__(self, db):
                self.db = db

            def dump(self):
                return self.db.conn.execute("SELECT 1")
    """) == []


def test_concurrency_real_tree_only_baselined_findings():
    """The live tree's DW3xx findings are exactly the triaged set in
    the checked-in baseline (each entry carries its ``why``)."""
    vs = [v for v in check_concurrency(repo_root())
          if v.code.startswith("DW3")]
    new, absorbed, stale = apply_baseline(vs, load_baseline())
    assert [v.render() for v in new] == []
    whys = load_whys()
    missing = [v.fingerprint() for v in absorbed
               if not whys.get(v.fingerprint())]
    assert missing == [], "baselined DW3xx entries must carry a why"


def test_baseline_why_survives_update(tmp_path):
    """--update-baseline rewrites entries but must carry over the
    justification of every surviving entry."""
    path = str(tmp_path / "baseline.json")
    write_baseline([_viol(), _viol(code="DW103")], path)
    data = json.loads(open(path).read())
    for e in data["violations"]:
        assert e["why"] == ""
        if e["code"] == "DW104":
            e["why"] = "intentional hits-gate sync"
    with open(path, "w") as f:
        json.dump(data, f)
    write_baseline([_viol()], path)   # DW103 fixed, DW104 survives
    data2 = json.loads(open(path).read())
    assert [e["why"] for e in data2["violations"]] == [
        "intentional hits-gate sync"]


def test_cli_explain_known_and_unknown_rule(capsys):
    from dwpa_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["--explain", "DW301"]) == 0
    out = capsys.readouterr().out
    assert "DW301" in out and "Example" in out
    assert cli_main(["--explain", "DW999"]) == 2


def test_summary_carries_per_rule_timings(tmp_path, capsys):
    from dwpa_tpu.analysis.__main__ import main as cli_main

    root = _conc_tree(tmp_path, "x = 1\n")
    empty = tmp_path / "b.json"
    empty.write_text('{"version": 1, "violations": []}')
    assert cli_main([root, "--baseline", str(empty)]) == 0
    out = capsys.readouterr().out
    for key in ("lint=", "DW301=", "DW302=", "DW303=", "DW304="):
        assert key in out
