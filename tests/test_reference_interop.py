"""Wire-protocol interop with the UNMODIFIED reference client.

Loads the real ``help_crack.py`` from the read-only reference checkout
(skipped when absent) and drives its own ``get_work`` / ``prepare_work``
/ ``put_work`` against our WSGI server over a live socket — proving the
README's claim that this server accepts stock volunteers, with the
reference's code as the contract instead of our reimplementation of it.
"""

import gzip
import hashlib
import importlib.util
import json
import os
import sys
import threading
import wsgiref.simple_server

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.server import Database, ServerCore, make_wsgi_app

HC_PATH = "/root/reference/help_crack/help_crack.py"

pytestmark = pytest.mark.skipif(
    not os.path.exists(HC_PATH), reason="reference checkout not present"
)

PSK = b"interop-psk99"
ESSID = b"InteropNet"


def _load_reference_client():
    spec = importlib.util.spec_from_file_location("help_crack_ref", HC_PATH)
    mod = importlib.util.module_from_spec(spec)
    argv = sys.argv
    sys.argv = ["help_crack.py"]
    try:
        spec.loader.exec_module(mod)
    except SystemExit:
        pass
    finally:
        sys.argv = argv
    return mod


@pytest.fixture
def live_server(tmp_path):
    core = ServerCore(Database(":memory:"), dictdir=str(tmp_path / "dicts"))
    core.add_hashlines(
        [tfx.make_pmkid_line(PSK, ESSID, seed="io1"),
         tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="io2")])
    core.db.x("UPDATE nets SET algo = ''")
    os.makedirs(core.dictdir, exist_ok=True)
    blob = gzip.compress(b"notit-0001\n" + PSK + b"\n")
    with open(os.path.join(core.dictdir, "io.txt.gz"), "wb") as f:
        f.write(blob)
    core.add_dict("dict/io.txt.gz", "io.txt.gz",
                  hashlib.md5(blob).hexdigest(), 2)
    srv = wsgiref.simple_server.make_server(
        "127.0.0.1", 0, make_wsgi_app(core))
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield core, f"http://127.0.0.1:{srv.server_port}/"
    srv.shutdown()
    srv.server_close()


def test_reference_client_full_unit(live_server, tmp_path, monkeypatch):
    core, base = live_server
    hc = _load_reference_client()
    hc.conf["base_url"] = base
    for key in ("get_work_url", "put_work_url", "prdict_url"):
        hc.conf[key] = base + "?" + key.split("_url")[0]
    hc.conf["format"] = "22000"  # what its hashcat probe would select
    # The reference client retries forever (sleepy(123)) on "No nets" or
    # malformed responses; a server regression must FAIL the test, not
    # wedge the suite.
    def fail_fast(self, sec=None):
        raise AssertionError("reference client entered its retry loop — "
                             "the server returned No nets/garbage")
    monkeypatch.setattr(hc.HelpCrack, "sleepy", fail_fast)
    monkeypatch.chdir(tmp_path)

    client = hc.HelpCrack(c=hc.conf)
    work = client.get_work(2)
    assert isinstance(work, dict) and {"hkey", "dicts", "hashes"} <= set(work)
    assert len(work["hashes"]) == 2  # same-ESSID grouping, like get_work.php

    # the reference client writes its own hash file from our payload
    client.prepare_work(work)
    lines = open("help_crack.hash").read().splitlines()
    assert len(lines) == 2 and all(ln.startswith("WPA*") for ln in lines)

    # the reference's dict download path verifies our md5 manifest
    d = work["dicts"][0]
    assert client.download(base + d["dpath"], "io.txt.gz")
    assert client.md5file("io.txt.gz") == d["dhash"]

    # submit the crack through the reference's own put_work
    mac_ap = work["hashes"][0].split("*")[3]
    client.put_work([{"k": mac_ap, "v": PSK.hex()}], work["hkey"])
    rows = core.db.q("SELECT n_state, pass FROM nets")
    assert all(r["n_state"] == 1 and r["pass"] == PSK for r in rows)
    assert core.db.q1(
        "SELECT COUNT(*) c FROM n2d WHERE hkey IS NOT NULL")["c"] == 0
