"""Differential tests for the Pallas PBKDF2 kernel.

On the CPU test platform the kernel runs in Pallas interpret mode, so the
iteration count is kept tiny; the device path is exercised (and verified
bit-exact against hashlib) by bench.py and the TPU-only test below.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from dwpa_tpu.models.m22000 import essid_salt_blocks
from dwpa_tpu.ops.pbkdf2 import pbkdf2_sha1_pmk
from dwpa_tpu.ops.pbkdf2_pallas import pbkdf2_sha1_pmk_pallas
from dwpa_tpu.ops.sha1 import sha1_compress_rolled
from dwpa_tpu.utils import bytesops as bo

ON_TPU = jax.devices()[0].platform == "tpu"


def _xla_pmk(pw_words, s1, s2, iterations):
    pw = [pw_words[:, i] for i in range(16)]
    return jnp.stack(
        pbkdf2_sha1_pmk(pw, list(s1), list(s2), iterations=iterations)
    )


def test_pallas_matches_xla_reduced_iterations():
    essid = b"unit-essid"
    s1, s2 = essid_salt_blocks(essid)
    pws = [b"password%02d" % i for i in range(5)]
    pw_words = jnp.asarray(bo.pack_passwords_be(pws))
    ref = np.asarray(_xla_pmk(pw_words, s1, s2, iterations=2))
    got = np.asarray(
        pbkdf2_sha1_pmk_pallas(
            pw_words,
            jnp.asarray(s1),
            jnp.asarray(s2),
            iterations=2,
            tile=8,
            interpret=not ON_TPU,
            prologue_compress=None if ON_TPU else sha1_compress_rolled,
        )
    )
    np.testing.assert_array_equal(got, ref)


def test_pallas_full_4096_matches_hashlib():
    if not ON_TPU:
        import pytest

        pytest.skip("full-iteration Pallas run needs the TPU (interpret too slow)")
    essid = b"unit-essid"
    s1, s2 = essid_salt_blocks(essid)
    pws = [b"longpassphrase-%04d" % i for i in range(64)]
    pw_words = jnp.asarray(bo.pack_passwords_be(pws))
    out = np.asarray(
        pbkdf2_sha1_pmk_pallas(pw_words, jnp.asarray(s1), jnp.asarray(s2))
    )
    for i in (0, 31, 63):
        ref = hashlib.pbkdf2_hmac("sha1", pws[i], essid, 4096, 32)
        assert bo.words_to_bytes_be(out[:, i]) == ref


def test_tpu_throughput_floor():
    """Regression floor for the hot kernel on real hardware: the r3
    pipelined mask path sustains ~240-265k PMK/s on a v5e chip; a drop
    below 150k means a kernel/pipeline regression, not tunnel noise
    (worst observed variance is ~±10%).  TPU-gated — CPU interpret mode
    measures nothing relevant."""
    if not ON_TPU:
        import pytest

        pytest.skip("throughput floor only meaningful on the TPU")
    import time

    from dwpa_tpu import testing as T
    from dwpa_tpu.models.m22000 import M22000Engine

    batch = 65536
    engine = M22000Engine(
        [T.make_pmkid_line(b"not-in-keyspace", b"floor-essid", seed="floor")],
        batch_size=batch,
    )
    n = 4 * batch
    engine.crack_mask("?d?d?d?d?d?d?d?d", skip=n, limit=batch)  # warm/compile
    t0 = time.perf_counter()
    engine.crack_mask("?d?d?d?d?d?d?d?d", skip=0, limit=n)
    rate = n / (time.perf_counter() - t0)
    assert rate > 150_000, f"kernel throughput regressed: {rate:.0f} PMK/s"
