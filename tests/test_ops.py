"""KAT + differential tests for the uint32-lane crypto primitives.

Every primitive is tested against hashlib/hmac (and FIPS-197 / RFC 4493
vectors for AES/CMAC), both scalar and batched, since the m22000 engine
relies on these exact semantics (reference oracle: web/common.php:157-307).
"""

import hashlib
import hmac as py_hmac

import numpy as np
import jax.numpy as jnp

from dwpa_tpu.ops import aes, hmac, md5, sha1, sha256
from dwpa_tpu.utils import bytesops as bo


def _digest(state_words, le=False):
    conv = bo.words_to_bytes_le if le else bo.words_to_bytes_be
    return conv([np.asarray(w) for w in state_words])


def test_sha1_kats():
    for msg in [b"", b"abc", b"a" * 63, b"b" * 64, b"c" * 65, b"d" * 1000]:
        got = _digest(sha1.sha1_digest_blocks(bo.message_blocks(msg)))
        assert got == hashlib.sha1(msg).digest(), msg


def test_md5_kats():
    for msg in [b"", b"abc", b"a" * 63, b"b" * 64, b"c" * 65, b"d" * 1000]:
        got = _digest(
            md5.md5_digest_blocks(bo.message_blocks(msg, little_endian=True)), le=True
        )
        assert got == hashlib.md5(msg).digest(), msg


def test_sha256_kats():
    for msg in [b"", b"abc", b"a" * 63, b"b" * 64, b"c" * 65, b"d" * 1000]:
        got = _digest(sha256.sha256_digest_blocks(bo.message_blocks(msg)))
        assert got == hashlib.sha256(msg).digest(), msg


def test_rolled_compress_variants():
    """The rolled (fori_loop) compressions must match the unrolled forms."""
    for msg in [b"abc", b"d" * 150]:
        blocks = bo.message_blocks(msg)
        st = sha1.sha1_init()
        for blk in blocks:
            st = sha1.sha1_compress_rolled(st, blk)
        assert _digest(st) == hashlib.sha1(msg).digest(), msg

        st = sha256.sha256_init()
        for blk in blocks:
            st = sha256.sha256_compress_rolled(st, blk)
        assert _digest(st) == hashlib.sha256(msg).digest(), msg

        st = md5.md5_init()
        for blk in bo.message_blocks(msg, little_endian=True):
            st = md5.md5_compress_rolled(st, blk)
        assert _digest(st, le=True) == hashlib.md5(msg).digest(), msg


def test_rolled_compress_batched():
    msgs = [b"alpha-block-one!", b"beta-block-two!!", b"gamma-block-3!!!"]
    blk = np.stack(
        [np.array(bo.message_blocks(m)[0], np.uint32) for m in msgs]
    )  # [3, 16]
    st = sha1.sha1_compress_rolled(
        sha1.sha1_init((3,)), [blk[:, w] for w in range(16)]
    )
    for i, msg in enumerate(msgs):
        got = bo.words_to_bytes_be([np.asarray(w)[i] for w in st])
        assert got == hashlib.sha1(msg).digest(), msg


def _key_block(key: bytes):
    return bo.be_words(key + b"\x00" * (64 - len(key)))


def _key_block_le(key: bytes):
    return bo.le_words(key + b"\x00" * (64 - len(key)))


def test_hmac_sha1_20():
    key = b"secret-key-0123456789ab"
    msg = b"exactly-twenty-bytes"
    i, o = hmac.hmac_sha1_precompute(_key_block(key))
    got = _digest(hmac.hmac_sha1_20(i, o, bo.be_words(msg)))
    assert got == py_hmac.new(key, msg, hashlib.sha1).digest()


def test_hmac_sha1_blocks_multiblock():
    key = b"\x01" * 32
    msg = b"Pairwise key expansion\x00" + b"\xaa" * 77  # 100 bytes, 2 blocks
    i, o = hmac.hmac_sha1_precompute(_key_block(key))
    got = _digest(
        hmac.hmac_sha1_blocks(i, o, bo.padded_blocks(msg, 64 + len(msg)))
    )
    assert got == py_hmac.new(key, msg, hashlib.sha1).digest()


def test_hmac_md5_blocks():
    key = b"\x02" * 16
    for n in [1, 60, 99, 121, 250]:
        msg = bytes(range(256))[:n]
        i, o = hmac.hmac_md5_precompute(_key_block_le(key))
        got = _digest(
            hmac.hmac_md5_blocks(
                i, o, bo.padded_blocks(msg, 64 + len(msg), little_endian=True)
            ),
            le=True,
        )
        assert got == py_hmac.new(key, msg, hashlib.md5).digest(), n


def test_hmac_sha256_blocks():
    key = b"\x03" * 32
    msg = b"\x01\x00Pairwise key expansion" + b"\xbb" * 78  # 102 bytes
    i, o = hmac.hmac_sha256_precompute(_key_block(key))
    got = _digest(
        hmac.hmac_sha256_blocks(i, o, bo.padded_blocks(msg, 64 + len(msg)))
    )
    assert got == py_hmac.new(key, msg, hashlib.sha256).digest()


def test_hmac_batched():
    """Batched keys must match per-key results (vectorization check)."""
    keys = [bytes([i]) * 32 for i in range(1, 5)]
    msg = b"exactly-twenty-bytes"
    kb = np.stack([np.array(_key_block(k), np.uint32) for k in keys])  # [4,16]
    kb_words = [kb[:, w] for w in range(16)]
    i, o = hmac.hmac_sha1_precompute(kb_words, shape=(4,))
    out = hmac.hmac_sha1_20(i, o, bo.be_words(msg))
    for n, key in enumerate(keys):
        got = bo.words_to_bytes_be([np.asarray(w)[n] for w in out])
        assert got == py_hmac.new(key, msg, hashlib.sha1).digest()


def test_aes128_fips197():
    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    ct = bytes.fromhex("69c4e0d86a7b0430d8cdb78070b4c55a")
    rks = aes.aes128_expand_key([jnp.uint32(b) for b in key])
    out = aes.aes128_encrypt_block(rks, [jnp.uint32(b) for b in pt])
    assert bytes(int(np.asarray(b)) for b in out) == ct


def test_aes128_cmac_rfc4493():
    key = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
    m = bytes.fromhex(
        "6bc1bee22e409f96e93d7e117393172a"
        "ae2d8a571e03ac9c9eb76fac45af8e51"
        "30c81c46a35ce411e5fbc1191a0a52ef"
        "f69f2445df4f9b17ad2b417be66c3710"
    )
    vectors = [
        (b"", "bb1d6929e95937287fa37d129b756746"),
        (m[:16], "070a16b46b4d4144f79bdd9dd04a287c"),
        (m[:40], "dfa66747de9ae63030ca32611497c827"),
        (m, "51f0bebf7e3b9d92fc49741779363cfe"),
    ]
    key16 = [jnp.uint32(b) for b in key]
    for msg, want in vectors:
        nfull = len(msg) // 16
        complete = len(msg) > 0 and len(msg) % 16 == 0
        if complete:
            blocks, last = msg[: (nfull - 1) * 16], msg[(nfull - 1) * 16 :]
        else:
            blocks, last = msg[: nfull * 16], msg[nfull * 16 :] + b"\x80"
        last = last + b"\x00" * (16 - len(last))
        mb = [list(blocks[i * 16 : (i + 1) * 16]) for i in range(len(blocks) // 16)]
        out = aes.aes128_cmac(key16, mb, list(last), complete)
        got = bytes(int(np.asarray(b)) for b in out)
        assert got == bytes.fromhex(want), (msg, got.hex())


def test_pack_passwords_be():
    pws = [b"aaaa1234", b"x" * 63, b"12345678"]
    arr = bo.pack_passwords_be(pws)
    assert arr.shape == (3, 16) and arr.dtype == np.uint32
    for i, pw in enumerate(pws):
        want = bo.be_words(pw + b"\x00" * (64 - len(pw)))
        assert list(arr[i]) == want, pw


def test_pbkdf2_sha1_pmk():
    import hashlib
    from dwpa_tpu.ops.pbkdf2 import pbkdf2_sha1_pmk
    from dwpa_tpu.utils.bytesops import padded_blocks

    essid = b"dlink"
    pws = [b"aaaa1234", b"password", b"x" * 63, b"12345678"]
    kb = bo.pack_passwords_be(pws)
    pw_words = [jnp.asarray(kb[:, w]) for w in range(16)]
    import struct

    s1 = padded_blocks(essid + struct.pack(">I", 1), 64 + len(essid) + 4)[0]
    s2 = padded_blocks(essid + struct.pack(">I", 2), 64 + len(essid) + 4)[0]
    pmk_words = pbkdf2_sha1_pmk(pw_words, s1, s2)
    for i, pw in enumerate(pws):
        got = bo.words_to_bytes_be([np.asarray(w)[i] for w in pmk_words])
        want = hashlib.pbkdf2_hmac("sha1", pw, essid, 4096, 32)
        assert got == want, pw


def test_sha1_hoisted_20_byte_specialization():
    """sha1_compress_20 (the PBKDF2 loop's hoisted-prologue form) is
    bit-identical to the generic compression over the fixed 20-byte
    HMAC message shape, for random states and messages — the CPU-side
    pin for the TPU kernel's hoist=True body."""
    import numpy as np

    from dwpa_tpu.ops.hmac import (
        hmac_sha1_20,
        hmac_sha1_20_hoisted,
        hmac_sha1_20_prologue,
    )
    from dwpa_tpu.ops.sha1 import sha1_20_prologue, sha1_compress, sha1_compress_20

    rng = np.random.default_rng(11)

    def rnd5():
        return tuple(
            jnp.asarray(rng.integers(0, 2**32, (9,), dtype=np.uint64).astype(np.uint32))
            for _ in range(5)
        )

    st, m5 = rnd5(), list(rnd5())
    blk = m5 + [0x80000000] + [0] * 9 + [84 * 8]
    for a, b in zip(sha1_compress(st, blk), sha1_compress_20(sha1_20_prologue(st), m5)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    ist, ost = rnd5(), rnd5()
    ref = hmac_sha1_20(ist, ost, m5)
    got = hmac_sha1_20_hoisted(hmac_sha1_20_prologue(ist, ost), m5)
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
