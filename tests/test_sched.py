"""Mixed-ESSID batch fusion (dwpa_tpu.sched + the per-lane-salt kernels).

Layers under test:

- the PER-LANE SALT kernel path — ``pmk_kernel`` with ``[B, 16]`` salt
  blocks bit-exact vs hashlib per lane, and the Pallas formulation's
  per-lane prologue vs the XLA path at reduced iterations;
- the PACKER (``sched.fuse``) — static width table properties, lane
  layout, store hit/miss composition;
- the ENGINE fused path (``crack_fused``) — differential against the
  serial per-unit path for mixed keyvers + mixed ESSIDs in ONE batch,
  found-PSK demux (a hit in unit A must not surface in unit B),
  resume-skip equivalence, and the recompile-sentinel proof that the
  fused widths keep XLA compiles bounded;
- the EXECUTOR (``sched.executor``) — wave assembly, ESSID-collision
  deferral, and the retry/requeue/backoff recovery contract.

Engine tests share ``BATCH = 32`` (fused widths {8, 16, 32} on the
8-device test mesh) so the per-lane PBKDF2 compiles are paid once.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from dwpa_tpu import testing as synth
from dwpa_tpu.models.m22000 import M22000Engine, essid_salt_blocks, pmk_kernel
from dwpa_tpu.obs import MetricsRegistry
from dwpa_tpu.obs.spans import SpanTracer
from dwpa_tpu.sched import (MultiUnitExecutor, WorkUnit, fuse_units,
                            fused_width, fused_widths)
from dwpa_tpu.utils import bytesops as bo

BATCH = 32


def _lane_salts(essids):
    """[B, 16] salt block pair for a per-lane ESSID assignment."""
    s1 = np.zeros((len(essids), 16), np.uint32)
    s2 = np.zeros((len(essids), 16), np.uint32)
    for i, e in enumerate(essids):
        s1[i], s2[i] = essid_salt_blocks(e)
    return s1, s2


# ---------------------------------------------------------------------------
# per-lane salt kernels
# ---------------------------------------------------------------------------


def test_per_lane_salt_kernel_matches_hashlib():
    """Lane b's PMK must be PBKDF2(pw_b, essid_b) exactly — the whole
    correctness contract of the fused path's salt gather."""
    essids = [b"LaneNetA", b"LaneNetB"]
    pws = [b"perlanepw%02d" % i for i in range(8)]
    lane_essid = [essids[i % 2] for i in range(8)]
    rows = bo.pack_passwords_be(pws).astype(np.uint32)
    s1, s2 = _lane_salts(lane_essid)
    pmk = np.asarray(pmk_kernel(rows, s1, s2))
    for i in range(8):
        ref = hashlib.pbkdf2_hmac("sha1", pws[i], lane_essid[i], 4096, 32)
        assert bo.words_to_bytes_be(pmk[:, i]) == ref


def test_scalar_salt_fast_path_unchanged():
    """uint32[16] salts still take the broadcast fast path and agree
    with the per-lane path when every lane shares one ESSID."""
    essid = b"ScalarNet"
    pws = [b"scalarpw%02d" % i for i in range(8)]
    rows = bo.pack_passwords_be(pws).astype(np.uint32)
    a, b = essid_salt_blocks(essid)
    scalar = np.asarray(pmk_kernel(rows, a, b))
    s1, s2 = _lane_salts([essid] * 8)
    np.testing.assert_array_equal(scalar, np.asarray(pmk_kernel(rows, s1, s2)))


def test_pallas_per_lane_prologue_matches_xla():
    """The Pallas formulation's per-lane U1 prologue (the ONLY part of
    the kernel the 2-D salt mode touches) against the XLA path, at
    reduced iterations (CPU interpret mode)."""
    from dwpa_tpu.ops.pbkdf2 import pbkdf2_sha1_pmk
    from dwpa_tpu.ops.pbkdf2_pallas import pbkdf2_sha1_pmk_pallas
    from dwpa_tpu.ops.sha1 import sha1_compress_rolled

    on_tpu = jax.devices()[0].platform == "tpu"
    pws = [b"fusedpw%03d" % i for i in range(6)]
    lane_essid = [b"PallasNet%d" % (i % 3) for i in range(6)]
    rows = jnp.asarray(bo.pack_passwords_be(pws))
    s1, s2 = _lane_salts(lane_essid)
    pw = [rows[:, i] for i in range(16)]
    ref = np.asarray(jnp.stack(pbkdf2_sha1_pmk(
        pw, [s1[:, i] for i in range(16)], [s2[:, i] for i in range(16)],
        iterations=2)))
    got = np.asarray(pbkdf2_sha1_pmk_pallas(
        rows, jnp.asarray(s1), jnp.asarray(s2), iterations=2, tile=8,
        interpret=not on_tpu,
        prologue_compress=None if on_tpu else sha1_compress_rolled))
    np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# the packer
# ---------------------------------------------------------------------------


def test_fused_widths_bounded_and_mesh_aligned():
    n = 8
    for batch in (32, 64, 4096, 16384):
        widths = fused_widths(batch, n)
        assert 1 <= len(widths) <= 3
        assert widths[-1] == batch
        assert all(w % n == 0 and w > 0 for w in widths)
        assert list(widths) == sorted(widths)
        for total in (0, 1, n, batch // 2, batch):
            w = fused_width(batch, n, total)
            assert w in widths and w >= total


def test_fuse_units_layout_and_fill():
    parts = [(b"FuseA", [b"alphaword%02d" % i for i in range(5)], 5),
             (b"FuseB", [b"betaword%03d" % i for i in range(3)], 3)]
    fb = fuse_units(parts, BATCH, 8, max_units=4)
    assert fb.total == 8 and fb.width == fused_width(BATCH, 8, 8)
    assert fb.nmiss == 8 and fb.idx is None  # no store: all-miss layout
    assert [u.lo for u in fb.units] == [0, 5]
    assert fb.fill == 8 / fb.width
    # lane-major unit_id: lanes 0-4 unit 0, lanes 5-7 unit 1, pad 0
    assert list(fb.unit_id[:8]) == [0] * 5 + [1] * 3
    # salt table rows are each unit's own blocks, padded with row 0
    s1a, _ = essid_salt_blocks(b"FuseA")
    s1b, _ = essid_salt_blocks(b"FuseB")
    np.testing.assert_array_equal(fb.table1[0], s1a)
    np.testing.assert_array_equal(fb.table1[1], s1b)
    np.testing.assert_array_equal(fb.table1[2], s1a)
    assert fb.table1.shape == (4, 16)


# ---------------------------------------------------------------------------
# engine: fused vs serial, demux, resume, recompiles
# ---------------------------------------------------------------------------


def _mixed_units():
    """Three units, three keyvers, three ESSIDs — one fused batch."""
    psks = [b"fusedpass-A1", b"fusedpass-B2", b"fusedpass-C3"]
    lines = [
        synth.make_pmkid_line(psks[0], b"MixNetA", seed="mx-a"),
        synth.make_eapol_line(psks[1], b"MixNetB", keyver=2, seed="mx-b"),
        synth.make_eapol_line(psks[2], b"MixNetC", keyver=3, seed="mx-c"),
    ]
    units = []
    for i, (essid, psk) in enumerate(
            zip([b"MixNetA", b"MixNetB", b"MixNetC"], psks)):
        words = [b"mixjunk%d%03d" % (i, j) for j in range(7)] + [psk]
        units.append((essid, words))
    return lines, units, psks


def test_fused_matches_serial_mixed_keyvers_and_essids():
    """The acceptance parity: mixed keyvers (pmkid/eapol/cmac) + mixed
    ESSIDs fused into one batch produce the identical found list the
    serial per-unit path produces (oracle verification on in both)."""
    lines, units, psks = _mixed_units()
    fused_eng = M22000Engine(lines, batch_size=BATCH)
    events = []
    fused = fused_eng.crack_fused(
        units, on_batch=lambda k, c, f: events.append((k, c)))
    serial = []
    for (essid, words), line in zip(units, lines):
        serial += M22000Engine([line], batch_size=BATCH).crack(words)
    key = lambda f: (f.line.essid, f.psk, f.nc, f.endian, f.pmk)
    assert sorted(map(key, fused)) == sorted(map(key, serial))
    assert sorted(f.psk for f in fused) == sorted(psks)
    # per-unit coverage reporting (the resume contract)
    assert sorted(events) == sorted((e, len(w)) for e, w in units)


def test_found_demux_no_cross_unit_leak():
    """The SAME password cracks unit A's net and appears in unit B's
    words too (B's net uses a different PSK): the hit must surface
    under unit A only — B's window sees the word under B's ESSID, where
    it does not match anything."""
    shared = b"shared-secret-pw"
    la = synth.make_pmkid_line(shared, b"DemuxA", seed="dm-a")
    lb = synth.make_pmkid_line(b"other-pass-b9", b"DemuxB", seed="dm-b")
    eng = M22000Engine([la, lb], batch_size=BATCH)
    by_unit = {}
    founds = eng.crack_fused(
        [(b"DemuxA", [b"demuxjunk%03d" % i for i in range(4)] + [shared]),
         (b"DemuxB", [shared] + [b"demuxjunk%03d" % i for i in range(4)])],
        on_batch=lambda k, c, f: by_unit.setdefault(k, []).extend(f))
    assert [f.psk for f in founds] == [shared]
    assert founds[0].line.essid == b"DemuxA"
    assert [f.line.essid for f in by_unit.get(b"DemuxA", [])] == [b"DemuxA"]
    assert by_unit.get(b"DemuxB", []) == []


def test_same_password_two_units_each_attributed():
    """Both nets share one password; the word rides in BOTH units: each
    unit's on_batch receives exactly its own net's find."""
    pw = b"both-nets-pass7"
    la = synth.make_pmkid_line(pw, b"AttrA", seed="at-a")
    lb = synth.make_eapol_line(pw, b"AttrB", keyver=2, seed="at-b")
    eng = M22000Engine([la, lb], batch_size=BATCH)
    by_unit = {}
    founds = eng.crack_fused(
        [(b"AttrA", [pw, b"attrjunk%03d" % 0]),
         (b"AttrB", [b"attrjunk%03d" % 1, pw])],
        on_batch=lambda k, c, f: by_unit.setdefault(k, []).extend(f))
    assert len(founds) == 2
    assert [f.line.essid for f in by_unit[b"AttrA"]] == [b"AttrA"]
    assert [f.line.essid for f in by_unit[b"AttrB"]] == [b"AttrB"]


def test_resume_skip_equivalence_under_fusion():
    """A unit resumed at skip=k through the executor covers exactly the
    serial path's unskipped tail: same found, and the consumed floor
    accounts skip + tail."""
    psk = b"resume-fused-1"
    line = synth.make_pmkid_line(psk, b"ResumeNet", seed="rs")
    words = [b"resumew%04d" % i for i in range(21)] + [psk]
    skip = 9
    ex = MultiUnitExecutor(
        [WorkUnit(uid=0, lines=[line], words=words, skip=skip)],
        batch_size=BATCH)
    done = ex.run()
    assert len(done) == 1 and [f.psk for f in done[0].founds] == [psk]
    assert done[0].consumed == len(words)  # skip + unskipped tail
    # serial reference over the identical tail
    serial = M22000Engine([line], batch_size=BATCH).crack(words[skip:])
    assert [f.psk for f in serial] == [psk]


def test_fused_width_sweep_recompile_bounded(recompile_sentinel):
    """The static-width proof for fusion: after one warmup per fused
    width, ANY unit mix — 1..4 units, any fill — reuses compiled
    programs (allowed=0).  Word lengths stay in one column-trim bucket
    so the sweep exercises only the width axis."""
    mesh_n = 8
    widths = fused_widths(BATCH, mesh_n)
    assert len(widths) <= 3

    def eng():
        # no PSK in keyspace: every batch takes the all-miss gate path
        return M22000Engine(
            [synth.make_pmkid_line(b"not-in-keyspace", b"SweepNet%d" % i,
                                   seed=f"sw{i}") for i in range(4)],
            batch_size=BATCH)

    n = 0

    def unit(essid_i, nwords):
        nonlocal n
        n += 1
        return (b"SweepNet%d" % essid_i,
                [b"sw%04d%03d" % (n, j) for j in range(nwords)])

    # warm every fused width once (single-unit batches)
    for w in widths:
        eng().crack_fused([unit(0, min(w, BATCH))])
    with recompile_sentinel(allowed=0, label="fused width sweep"):
        eng().crack_fused([unit(0, 3), unit(1, 2)])            # small width
        eng().crack_fused([unit(i, 3) for i in range(4)])      # mid width
        eng().crack_fused([unit(i, 8) for i in range(4)])      # full width
        eng().crack_fused([unit(2, 1)])                        # tiny again


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------


def _units(k, prefix=b"ExNet", psk_fmt=b"expass%03d", nwords=6):
    out = []
    for i in range(k):
        psk = psk_fmt % i
        line = synth.make_pmkid_line(psk, prefix + b"%d" % i, seed=f"ex{i}")
        words = [b"exwords%d%03d" % (i, j) for j in range(nwords)] + [psk]
        out.append(WorkUnit(uid=i, lines=[line], words=words))
    return out


def test_executor_metrics_and_spans():
    reg = MetricsRegistry()
    tracer = SpanTracer(reg)
    ex = MultiUnitExecutor(_units(3), batch_size=BATCH, unit_queue=3,
                           fuse_max_units=4, registry=reg, tracer=tracer)
    done = ex.run()
    assert len(done) == 3 and all(len(u.founds) == 1 for u in done)
    assert reg.value("dwpa_fused_units_per_batch") >= 1  # histogram count
    assert 0.0 < reg.value("dwpa_fused_fill_fraction") <= 1.0
    assert reg.value("dwpa_unit_queue_depth") is not None
    names = {r["name"] for r in tracer.records()}
    assert {"sched:fuse", "sched:demux"} <= names


def test_executor_essid_collision_defers_to_next_wave():
    """Two units over the SAME ESSID cannot share a salt-table row; the
    second waits one wave and both still complete."""
    psk1, psk2 = b"collide-one1", b"collide-two2"
    line = synth.make_pmkid_line(psk1, b"CollideNet", seed="co")
    u1 = WorkUnit(uid=1, lines=[line], words=[psk1, b"cjunkcjunk1"])
    u2 = WorkUnit(uid=2, lines=[line], words=[b"cjunkcjunk2", psk1])
    ex = MultiUnitExecutor([u1, u2], batch_size=BATCH, fuse_max_units=4)
    done = ex.run()
    assert {u.uid for u in done} == {1, 2}
    # the first unit to crack the net wins; the other covers its words
    assert sum(len(u.founds) for u in done) >= 1
    assert all(u.consumed == 2 for u in done)


def test_executor_retry_halves_batch_then_requeues():
    """Satellite recovery contract: a raising wave retries once at half
    batch; persistent failure requeues with backoff until max_retries,
    then the unit lands in ``failed`` instead of wedging the stream."""
    units = _units(1)
    attempts = []

    class _Boom:
        def crack_fused(self, *a, **k):
            raise RuntimeError("injected device error")

    def factory(lines, batch_size):
        attempts.append(batch_size)
        return _Boom()

    reg = MetricsRegistry()
    slept = []
    ex = MultiUnitExecutor(units, batch_size=BATCH, registry=reg,
                           engine_factory=factory, max_retries=2,
                           backoff_s=0.5, sleep=slept.append)
    done = ex.run()
    assert done == [] and ex.failed == units
    # per failed wave: one try at BATCH, one at BATCH // 2
    assert attempts == [BATCH, BATCH // 2] * 3
    assert slept == [0.5, 1.0]  # exponential backoff between requeues
    assert reg.value("dwpa_fused_retries_total") == 3


def test_executor_recovers_on_transient_error():
    """One transient failure: the half-batch retry completes the wave
    and the unit still cracks."""
    units = _units(2)
    state = {"raised": False}

    def factory(lines, batch_size):
        if not state["raised"]:
            state["raised"] = True

            class _Boom:
                def crack_fused(self, *a, **k):
                    raise RuntimeError("transient")

            return _Boom()
        return M22000Engine(lines, batch_size=batch_size)

    ex = MultiUnitExecutor(units, batch_size=BATCH, engine_factory=factory)
    done = ex.run()
    assert len(done) == 2 and all(len(u.founds) == 1 for u in done)
    assert ex.failed == []


def test_executor_leaves_no_orphan_threads():
    """Thread-lifecycle audit: run() joins its unit producer (and the
    per-device stream drainers join inside the wave), so no ``sched-*``
    thread survives a completed run — the feed-soak no-orphan idiom
    extended to the executor."""
    import threading

    def _sched_threads():
        return [t for t in threading.enumerate()
                if t.name.startswith("sched-") and t.is_alive()]

    psk = b"orphan-check-1"
    line = synth.make_pmkid_line(psk, b"OrphanNet", seed="oc")
    units = [WorkUnit(uid=i, lines=[line],
                      words=[b"w%04d" % i, psk])
             for i in range(3)]
    ex = MultiUnitExecutor(units, batch_size=BATCH, unit_queue=2)
    done = ex.run()
    assert len(done) == 3
    deadline = __import__("time").time() + 10.0
    while _sched_threads() and __import__("time").time() < deadline:
        for t in _sched_threads():
            t.join(timeout=0.2)
    assert _sched_threads() == []
