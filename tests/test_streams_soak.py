"""Slow soak: two device streams over a long framed feed.

Tier-1 runs the fast stream units (tests/test_streams.py); this soak —
``-m slow``, ~30 s — drives two REAL single-device streams through
many blocks with founds scattered across the stream, a mid-run crash
on one stream, and asserts the long-haul contract: every block is
demuxed exactly once in global order (consumed totals and on_batch
sequence intact), the crashed stream's blocks finish on the survivor,
found parity against the lockstep path holds over the whole run, and
no stream thread outlives the executor.
"""

import threading

import jax
import pytest

from dwpa_tpu import testing as synth
from dwpa_tpu.feed import frame_blocks
from dwpa_tpu.models.m22000 import M22000Engine
from dwpa_tpu.parallel import StreamExecutor
from dwpa_tpu.parallel.streams import device_label

pytestmark = pytest.mark.slow

BATCH = 32
NBLOCKS = 40


def _stream_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("stream-")]


def _fixture():
    """Six crackable nets on THREE shared ESSIDs plus one uncracked net.

    The ESSID count is deliberate: the lockstep reference path runs one
    collective-bearing ``shard_map`` step per ESSID group per block, and
    the forced-host CPU backend deadlocks its AllReduce rendezvous when
    too many such executions are in flight at once (7 groups stall even
    at 10 blocks; 4 groups survive 40+).  The stream path has no such
    limit — its per-device engines carry no collectives — which is
    exactly the point of this executor, but the reference run must stay
    inside the lockstep-safe envelope.
    """
    psks = [b"soak-stream-%02d" % i for i in range(6)]
    lines = [synth.make_pmkid_line(p, b"SoakStream%c" % (65 + i % 3),
                                   seed=f"ss{i}")
             for i, p in enumerate(psks)]
    # one net stays uncracked so neither path early-stops
    lines.append(synth.make_pmkid_line(b"never-found-here", b"SoakStreamX",
                                       seed="ssx"))
    words = [b"soakjunk%05d" % i for i in range(BATCH * NBLOCKS)]
    for i, p in enumerate(psks):     # scatter founds across the stream
        words[7 + i * (len(words) // len(psks))] = p
    return lines, words, psks


def test_two_stream_soak_parity_with_crash_recovery():
    lines, words, psks = _fixture()
    devices = jax.devices()[:2]

    lock_eng = M22000Engine(lines, batch_size=BATCH)
    lock_log = []
    lock_founds = lock_eng.crack_blocks(
        frame_blocks(iter(words), lock_eng.batch_size),
        on_batch=lambda c, f: lock_log.append((c, sorted(x.psk for x in f))))

    st_eng = M22000Engine(lines, batch_size=BATCH)
    sub = {}

    class _CrashOnce:
        """Engine proxy that kills stream 0 once, mid-run."""

        armed = True

        def __init__(self, eng):
            self._eng = eng
            self.dispatched = 0

        def __getattr__(self, name):
            return getattr(self._eng, name)

        def _dispatch(self, prep):
            self.dispatched += 1
            if type(self).armed and self.dispatched == NBLOCKS // 4:
                type(self).armed = False
                raise RuntimeError("injected mid-soak stream crash")
            return self._eng._dispatch(prep)

    def factory(device):
        from dwpa_tpu.parallel import default_mesh

        eng = M22000Engine([n.line for n in st_eng.nets], nc=st_eng.nc,
                           batch_size=st_eng.batch_size,
                           mesh=default_mesh(devices=[device]))
        sub[device_label(device)] = eng
        if len(sub) == 1:            # first stream built gets the crash
            return _CrashOnce(eng)
        return eng

    ex = StreamExecutor(factory, devices)
    st_log = []
    st_founds = ex.run(
        frame_blocks(iter(words), st_eng.batch_size),
        on_batch=lambda c, f: st_log.append((c, sorted(x.psk for x in f))))

    keys = lambda fs: sorted((f.line.essid, f.psk, f.pmk) for f in fs)
    assert keys(st_founds) == keys(lock_founds)
    assert {f.psk for f in st_founds} == set(psks)
    assert st_log == lock_log
    assert sum(c for c, _ in st_log) == len(words)
    assert len(ex.block_streams) == NBLOCKS
    # the crash really happened and the survivor carried extra blocks
    assert not _CrashOnce.armed
    assert ex.block_streams.count(1) > ex.block_streams.count(0)
    assert _stream_threads() == []
