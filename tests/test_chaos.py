"""Chaos-harness tests: retry policy, circuit breaker, fault transports,
and the seeded end-to-end soak.

Fast tests pin the unit behavior of every resilience primitive
(``RetryPolicy``, ``CircuitBreaker``, ``classify_error``, ``FaultPlan``,
``ChaosTransport``) plus the loopback 4xx/5xx classification contract.
The ``slow``-marked soak runs a full loopback work unit under a seeded
fault schedule — at least one timeout, 5xx, truncated body, put_work
reject and a mid-unit client restart — and asserts parity with the
fault-free run: no founds lost, no duplicate accepted submissions,
identical fault schedule from the same seed, clean thread teardown.
"""

import gzip
import hashlib
import os
import random
import threading
import time
import urllib.error

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.chaos import (ChaosTransport, FaultPlan, VirtualClock,
                            WsgiTransport)
from dwpa_tpu.client.main import ClientConfig, TpuCrackClient
from dwpa_tpu.client.protocol import (CircuitBreaker, CircuitOpenError,
                                      NoNets, PermanentError, RetryPolicy,
                                      ServerAPI, classify_error)
from dwpa_tpu.obs import MetricsRegistry
from dwpa_tpu.server import Database, ServerCore, make_wsgi_app

PSK = b"chaos-psk-001"
ESSID = b"ChaosNet"


# -- fixtures --------------------------------------------------------------


def _server(tmp_path, sub="srv"):
    db = Database(":memory:")
    return ServerCore(db, dictdir=str(tmp_path / sub / "dicts"),
                      capdir=str(tmp_path / sub / "caps"))


def _add_dict(core, words, name="chaos.txt.gz"):
    os.makedirs(core.dictdir, exist_ok=True)
    blob = gzip.compress(b"\n".join(words) + b"\n")
    with open(os.path.join(core.dictdir, name), "wb") as f:
        f.write(blob)
    core.add_dict(f"dict/{name}", name, hashlib.md5(blob).hexdigest(),
                  len(words), rules=None)


def _ingest(core, lines):
    core.add_hashlines(lines)
    core.db.x("UPDATE nets SET algo = ''")  # release to volunteers


def _api(app, plan=None, clock=None, **kw):
    """Real ServerAPI over the in-process WSGI app at the ``_transport``
    seam — classification, backoff and the breaker run for real."""
    clock = clock if clock is not None else VirtualClock()
    kw.setdefault("max_tries", 0)
    kw.setdefault("backoff", 0.5)
    kw.setdefault("rng", random.Random(11))
    kw.setdefault("sleep", clock.sleep)
    kw.setdefault("breaker", CircuitBreaker(threshold=3, cooldown=4.0,
                                            clock=clock.now))
    api = ServerAPI("http://loopback/", **kw)
    api.retry.clock = clock.now
    wsgi = WsgiTransport(app)
    api._transport = wsgi if plan is None else ChaosTransport(
        wsgi, plan, sleep=clock.sleep)
    return api, wsgi, clock


def _client(core, workdir, plan, clock, **cfg_kw):
    cfg_kw.setdefault("batch_size", 64)
    cfg_kw.setdefault("dictcount", 1)
    cfg_kw.setdefault("device_streams", "off")
    cfg = ClientConfig(base_url="http://loopback/", workdir=str(workdir),
                       **cfg_kw)
    api, wsgi, _ = _api(make_wsgi_app(core), plan=plan, clock=clock)
    client = TpuCrackClient(cfg, api=api, log=lambda *a, **k: None)
    return client, wsgi


# -- FaultPlan -------------------------------------------------------------


def test_fault_plan_same_seed_identical_schedule():
    endpoints = ["get_work", "put_work", "download", "get_work"] * 10
    a = FaultPlan(42, rate=0.5)
    b = FaultPlan(42, rate=0.5)
    for ep in endpoints:
        a.next_fault(ep)
        b.next_fault(ep)
    assert a.schedule() == b.schedule()
    assert a.kinds_injected()  # 50% over 40 calls: something fired
    assert FaultPlan(43, rate=0.5) is not None  # different seed differs
    c = FaultPlan(43, rate=0.5)
    for ep in endpoints:
        c.next_fault(ep)
    assert c.schedule() != a.schedule()


def test_fault_plan_force_fifo_and_validation():
    plan = FaultPlan(0).force("get_work", "timeout").force("get_work",
                                                           "http_5xx")
    assert plan.next_fault("put_work") is None  # forces are per-endpoint
    assert plan.next_fault("get_work") == "timeout"
    assert plan.next_fault("get_work") == "http_5xx"
    assert plan.next_fault("get_work") is None  # rate 0: nothing random
    with pytest.raises(ValueError):
        plan.force("get_work", "nonsense")


# -- RetryPolicy -----------------------------------------------------------


def test_retry_policy_deterministic_and_bounded():
    delays_a = []
    st = RetryPolicy(base=2.0, cap=10.0, rng=random.Random(7)).start(0)
    for _ in range(50):
        delays_a.append(st.next_delay())
    st2 = RetryPolicy(base=2.0, cap=10.0, rng=random.Random(7)).start(0)
    delays_b = [st2.next_delay() for _ in range(50)]
    assert delays_a == delays_b  # injectable rng: exact replay
    assert all(2.0 <= d <= 10.0 for d in delays_a)
    assert max(delays_a) > 2.0  # jitter actually ramps off the base


def test_retry_policy_flat_reference_parity():
    # base == cap (the default) degenerates to the reference client's
    # flat 123 s cadence.
    st = RetryPolicy(base=123.0, rng=random.Random(1)).start(0)
    assert [st.next_delay() for _ in range(5)] == [123.0] * 5


def test_retry_policy_max_tries_and_deadline():
    st = RetryPolicy(base=1.0, rng=random.Random(3)).start(3)
    assert st.next_delay() is not None  # after attempt 1
    assert st.next_delay() is not None  # after attempt 2
    assert st.next_delay() is None      # attempt 3 was the last

    clock = VirtualClock()
    pol = RetryPolicy(base=5.0, deadline=12.0, rng=random.Random(3),
                      clock=clock.now)
    st = pol.start(0)
    spent = 0.0
    while True:
        d = st.next_delay()
        if d is None:
            break
        clock.sleep(d)
        spent += d
    assert spent <= 12.0  # delays are clamped into the budget


# -- CircuitBreaker --------------------------------------------------------


def test_circuit_breaker_lifecycle():
    clock = VirtualClock()
    br = CircuitBreaker(threshold=3, cooldown=10.0, clock=clock.now)
    assert br.allow() and br.state == br.CLOSED
    br.record_failure()
    br.record_failure()
    assert br.state == br.CLOSED  # below threshold
    br.record_failure()
    assert br.state == br.OPEN
    assert not br.allow()
    assert br.remaining() == pytest.approx(10.0)
    clock.sleep(10.0)
    assert br.allow()  # exactly one probe admitted
    assert br.state == br.HALF_OPEN
    br.record_success()
    assert br.state == br.CLOSED and br.failures == 0
    # A failed probe reopens with a fresh cooldown window.
    for _ in range(3):
        br.record_failure()
    clock.sleep(10.0)
    assert br.allow()
    br.record_failure()
    assert br.state == br.OPEN and br.remaining() > 0


# -- classification --------------------------------------------------------


def test_classify_error_table():
    import io

    http = lambda code: urllib.error.HTTPError("u", code, "m", None,
                                               io.BytesIO(b""))
    assert classify_error(http(404)) == ("permanent", "http_4xx")
    assert classify_error(http(400)) == ("permanent", "http_4xx")
    assert classify_error(http(503)) == ("transient", "http_5xx")
    # HTTPError subclasses URLError: the 4xx row above IS the regression
    # test for the reference bug (URLError caught first retried forever).
    assert isinstance(http(404), urllib.error.URLError)
    assert classify_error(TimeoutError()) == ("transient", "timeout")
    assert classify_error(
        urllib.error.URLError(ConnectionRefusedError())) == (
        "transient", "refused")
    assert classify_error(
        urllib.error.URLError(ConnectionResetError())) == (
        "transient", "reset")
    assert classify_error(urllib.error.URLError("dns")) == (
        "transient", "unreachable")
    assert classify_error(ConnectionResetError()) == ("transient", "reset")
    assert classify_error(OSError("disk")) == ("transient", "conn")


# -- ChaosTransport --------------------------------------------------------


def test_chaos_transport_kinds():
    calls = []

    def inner(url, body=None, headers=None):
        calls.append(url)
        return b'{"some": "body"}'

    url = "http://x/?get_work=2.2.0"
    slept = []
    plan = FaultPlan(0)
    t = ChaosTransport(inner, plan, sleep=slept.append, slow_s=0.25)

    # Pre-exchange kinds raise WITHOUT touching the inner transport.
    for kind, exc in (("drop", ConnectionResetError),
                      ("timeout", TimeoutError),
                      ("http_4xx", urllib.error.HTTPError),
                      ("http_5xx", urllib.error.HTTPError)):
        plan.force("get_work", kind)
        with pytest.raises(exc):
            t(url)
    assert calls == []

    # Post-exchange kinds complete the exchange, then corrupt the reply.
    plan.force("get_work", "truncate")
    assert t(url) == b'{"some": "body"}'[:8]
    plan.force("get_work", "garbage")
    out = t(url)
    assert out != b'{"some": "body"}'
    plan.force("get_work", "reject")
    assert t(url) == b"chaos: rejected"
    plan.force("get_work", "slow")
    assert t(url) == b'{"some": "body"}' and slept == [0.25]
    assert len(calls) == 4
    assert t(url) == b'{"some": "body"}'  # no fault: clean pass-through


# -- transport stack over the loopback server ------------------------------


def test_http_4xx_fails_fast(tmp_path):
    """The satellite regression: an HTTP 4xx must classify permanent and
    raise after ONE exchange — never enter the retry loop (the reference
    bug: HTTPError ⊂ URLError, so a 404 retried forever)."""
    core = _server(tmp_path)

    def no_sleep(_):
        raise AssertionError("slept on a 4xx: permanent error was retried")

    api, wsgi, _ = _api(make_wsgi_app(core), sleep=no_sleep)
    with pytest.raises(PermanentError):
        api.fetch("http://loopback/no/such/path")
    assert len(wsgi.requests) == 1
    assert not api.circuit_open  # a reachable server never trips it


def test_http_5xx_retries_then_succeeds(tmp_path):
    core = _server(tmp_path)
    plan = FaultPlan(0).force("get_work", "http_5xx").force("get_work",
                                                            "http_5xx")
    api, wsgi, clock = _api(make_wsgi_app(core), plan=plan)
    reg = MetricsRegistry()
    api.bind_obs(reg)
    with pytest.raises(NoNets):  # empty server: success body is "No nets"
        api.get_work(1)
    assert len(wsgi.requests) == 1  # only the clean third exchange landed
    assert reg.value("dwpa_client_retries_total",
                     endpoint="get_work", reason="http_5xx") == 2
    assert clock.now() > 0  # backoff actually slept (on the fake clock)


def test_get_work_garbage_goes_permanent(tmp_path):
    core = _server(tmp_path)
    plan = FaultPlan(0)
    for _ in range(8):
        plan.force("get_work", "garbage")
    api, wsgi, _ = _api(make_wsgi_app(core), plan=plan)
    with pytest.raises(PermanentError, match="malformed get_work"):
        api.get_work(1)
    # validation_retries re-fetches, then gives up: bounded exchanges.
    assert len(wsgi.requests) == api.validation_retries + 1


def test_circuit_opens_blocks_bounded_then_probe_recovers(tmp_path):
    core = _server(tmp_path)
    down = lambda url, body=None, headers=None: (_ for _ in ()).throw(
        ConnectionRefusedError("chaos: down"))
    api, wsgi, clock = _api(make_wsgi_app(core))
    reg = MetricsRegistry()
    api.bind_obs(reg)
    live = api._transport
    api._transport = down

    # threshold=3 consecutive failures trip the breaker mid-retry; the
    # bounded caller then fails fast instead of burning its budget.
    with pytest.raises(CircuitOpenError):
        api.fetch(api._endpoint("get_work=2.2.0"), max_tries=10)
    assert api.circuit_open
    assert reg.value("dwpa_client_circuit_state") == CircuitBreaker.OPEN

    # Still inside the cooldown: fail fast again, no transport call.
    with pytest.raises(CircuitOpenError):
        api.fetch(api._endpoint("get_work=2.2.0"), max_tries=2)

    # Past the cooldown the single probe goes through; a healthy reply
    # closes the circuit.
    clock.sleep(api.breaker.cooldown)
    api._transport = live
    with pytest.raises(NoNets):
        api.get_work(1)
    assert not api.circuit_open
    assert reg.value("dwpa_client_circuit_state") == CircuitBreaker.CLOSED


# -- degraded mode ---------------------------------------------------------


def test_degraded_mode_cracks_buffered_units(tmp_path):
    """With the transport down, prefetched units keep the devices busy
    and every found lands in the outbox; the drain delivers them once
    the server is back — nothing lost, nothing duplicated."""
    core = _server(tmp_path)
    psk_a, psk_b = b"chaos-psk-00A", b"chaos-psk-00B"
    _ingest(core, [tfx.make_pmkid_line(psk_a, b"ChaosNetA", seed="dgA"),
                   tfx.make_pmkid_line(psk_b, b"ChaosNetB", seed="dgB")])
    _add_dict(core, [b"nope-000001", psk_a, psk_b])

    # 3 forced drops: put_work attempt x2 (bounded by the outbox-backed
    # submit), then the between-units drain probe — the third failure
    # opens the breaker, and everything after fails fast.
    plan = FaultPlan(5)
    for _ in range(3):
        plan.force("put_work", "drop")
    clock = VirtualClock()
    client, wsgi = _client(core, tmp_path / "w", plan, clock,
                           prefetch_units=1, max_work_units=2)

    assert client.run() == 2  # both units cracked despite the dead put path
    assert client.api.circuit_open
    assert client.outbox.pending_count() == 2  # one found per unit, safe
    assert core.db.q1(
        "SELECT COUNT(*) c FROM nets WHERE n_state = 1")["c"] == 0

    # Server back (forced faults exhausted) + cooldown passed: drain.
    clock.sleep(client.api.breaker.cooldown)
    client._drain_outbox()
    assert client.outbox.pending_count() == 0
    assert not client.api.circuit_open
    rows = core.db.q("SELECT n_state, pass FROM nets")
    assert sorted(r["pass"] for r in rows) == [psk_a, psk_b]
    assert all(r["n_state"] == 1 for r in rows)


# -- the seeded soak -------------------------------------------------------


@pytest.mark.slow
def test_chaos_soak_full_unit_parity(tmp_path, lock_witness):
    SEED = 20260805
    lines = [tfx.make_pmkid_line(PSK, ESSID, seed="cs1"),
             tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="cs2")]
    words = [b"nope-%06d" % i for i in range(60)] + [PSK]
    RATE_KINDS = ("drop", "timeout", "http_5xx", "slow")

    def build_server(sub):
        core = _server(tmp_path, sub=sub)
        _ingest(core, lines)
        _add_dict(core, words)
        return core

    # Leg 1: fault-free baseline.
    core0 = build_server("s0")
    client0, _ = _client(core0, tmp_path / "w0", FaultPlan(SEED), VirtualClock())
    work0 = client0.api.get_work(1)
    res0 = client0.process_work(work0)
    assert res0.accepted
    founds0 = sorted(f.psk for f in res0.founds)
    assert founds0 == [PSK, PSK]
    state0 = sorted((r["n_state"], r["pass"])
                    for r in core0.db.q("SELECT n_state, pass FROM nets"))

    def make_plan():
        plan = FaultPlan(SEED, rate=0.10, kinds=RATE_KINDS)
        # Acceptance floor: at least one of each, deterministically.
        plan.force("get_work", "timeout")
        plan.force("get_work", "http_5xx")
        # Body corruption goes on put_work, where the server has already
        # processed the request — the exactly-once hazard the outbox
        # covers.  (A torn get_work body would strand the server-side
        # lease until reap: the re-fetch finds everything leased.)
        plan.force("put_work", "truncate")  # server accepted, reply torn
        plan.force("put_work", "reject")
        return plan

    # The witness watches every lock leg 2 creates (client, feed,
    # outbox, server core): a cycle in the witnessed acquisition
    # order fails the soak even when the interleaving got lucky.
    with lock_witness(label="chaos soak leg 2"):
        # Leg 2: same servers-side state, seeded chaos schedule.
        core1 = build_server("s1")
        plan = make_plan()
        clock = VirtualClock()
        threads_before = set(threading.enumerate())
        client1, wsgi1 = _client(core1, tmp_path / "w1", plan, clock)
        work1 = client1.api.get_work(1)  # survives timeout, 5xx, torn body

        # Mid-unit client restart: checkpoint, then a fresh process over the
        # same workdir replays the unit instead of fetching new work.
        client1._write_resume(work1)
        client2, _ = _client(core1, tmp_path / "w1", plan, clock)
        replayed = client2._read_resume()
        assert replayed == work1

        res1 = client2.process_work(replayed)
        founds1 = sorted(f.psk for f in res1.founds)
        assert founds1 == founds0  # no founds lost under faults

        # First put_work reply was torn, the drain's hit the forced reject:
        # the founds sit durably in the outbox until a clean exchange lands.
        for _ in range(10):
            if not client2.outbox.pending_count():
                break
            clock.sleep(client2.api.breaker.cooldown)
            try:
                client2._drain_outbox()
            except ConnectionError:
                continue
        assert client2.outbox.pending_count() == 0

        # Server-side parity with the fault-free leg: same nets cracked to
        # the same PSK, no extra rows — repeated put_work exchanges (torn
        # reply + redrives) never produced a duplicate accepted submission.
        state1 = sorted((r["n_state"], r["pass"])
                        for r in core1.db.q("SELECT n_state, pass FROM nets"))
        assert state1 == state0
        assert core1.db.q1("SELECT COUNT(*) c FROM nets")["c"] == len(lines)
        # The processed unit's lease is consumed exactly like the clean leg.
        assert core1.db.q1("SELECT COUNT(*) c FROM n2d WHERE hkey = ?",
                           (replayed["hkey"],))["c"] == 0
        # Resume cleared on both legs.
        assert not os.path.exists(client0.resume_path)
        assert not os.path.exists(client2.resume_path)

        # Every required fault kind actually fired.
        assert {"timeout", "http_5xx", "truncate",
                "reject"} <= plan.kinds_injected()

        # Same seed -> bit-identical fault schedule over the same calls.
        replay = make_plan()
        for _, endpoint, _ in plan.schedule():
            replay.next_fault(endpoint)
        assert replay.schedule() == plan.schedule()

        # Clean teardown: nothing the run spawned is still alive.
        deadline = time.time() + 10.0
        while time.time() < deadline:
            spawned = [t for t in set(threading.enumerate()) - threads_before
                       if t.is_alive()]
            if not spawned:
                break
            for t in spawned:
                t.join(timeout=0.5)
        assert not spawned, f"threads leaked: {spawned}"
