"""UI pages (visibility tiers) + user-key/mail lifecycle tests.

Reference behavior being matched: web/content/nets.php:17-53 (three
tiers), search.php:12-117, stats.php, my_nets.php, dicts.php;
web/index.php:48-142 + get_key.php:11-31 (key issue, 24h throttle,
linkkey confirmation, cookie set/remove).
"""

import io
import urllib.parse

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.models import hashline as hl
from dwpa_tpu.server import Database, ServerCore, make_wsgi_app
from dwpa_tpu.server.mail import CapturingMailer
from dwpa_tpu.server import ui

PSK = b"tiers-psk-01"
ESSID = b"TierNet"
BOSSKEY = "b" * 32


@pytest.fixture
def core(tmp_path):
    db = Database(":memory:")
    return ServerCore(db, dictdir=str(tmp_path / "d"), capdir=str(tmp_path / "c"),
                      mailer=CapturingMailer(), bosskey=BOSSKEY)


def _call(app, method="GET", qs="", body=b"", ctype=None, cookie=None):
    out = {}

    def sr(status, headers):
        out["status"], out["headers"] = status, headers

    environ = {
        "REQUEST_METHOD": method,
        "PATH_INFO": "/",
        "QUERY_STRING": qs,
        "CONTENT_LENGTH": str(len(body)),
        "wsgi.input": io.BytesIO(body),
        "REMOTE_ADDR": "8.8.4.4",
        "HTTP_ACCEPT": "text/html",
    }
    if ctype:
        environ["CONTENT_TYPE"] = ctype
    if cookie:
        environ["HTTP_COOKIE"] = f"key={cookie}"
    resp = b"".join(app(environ, sr))
    return out["status"], dict(out["headers"]), resp


def _form(app, qs, fields, cookie=None):
    body = urllib.parse.urlencode(fields).encode()
    return _call(app, "POST", qs, body,
                 ctype="application/x-www-form-urlencoded", cookie=cookie)


def _cracked_net(core, userkey=None):
    line = tfx.make_pmkid_line(PSK, ESSID, seed="ui1")
    core.add_hashlines([line], userkey=userkey)
    nhash = core.db.q1("SELECT hash FROM nets")["hash"]
    core.put_work({"type": "hash", "cand": [{"k": nhash.hex(), "v": PSK.decode()}]})
    return line


# -- visibility tiers ------------------------------------------------------


def test_nets_tiers(core):
    app = make_wsgi_app(core)
    owner_key = core.create_user("owner@example.com")
    _cracked_net(core, userkey=owner_key)
    # second net owned by nobody, also cracked
    other = tfx.make_pmkid_line(PSK, b"OtherTier", seed="ui2")
    core.add_hashlines([other])
    ohash = hl.parse(other)
    core.put_work({"type": "hash",
                   "cand": [{"k": core.db.q1(
                       "SELECT hash FROM nets WHERE ssid = ?", (b"OtherTier",)
                   )["hash"].hex(), "v": PSK.decode()}]})

    # anonymous: placeholders only
    _, _, anon = _call(app, qs="nets")
    assert b"Found" in anon and PSK not in anon

    # bosskey: all passwords
    _, _, boss = _call(app, qs="nets", cookie=BOSSKEY)
    assert boss.count(PSK) == 2

    # keyed user: own password in clear, foreign as placeholder
    _, _, keyed = _call(app, qs="nets", cookie=owner_key)
    assert keyed.count(PSK) == 1 and b"Found" in keyed


def test_uncracked_net_renders_guess_input_and_accepts_claim(core):
    app = make_wsgi_app(core)
    line = tfx.make_pmkid_line(PSK, ESSID, seed="ui3")
    core.add_hashlines([line])
    nhash = core.db.q1("SELECT hash FROM nets")["hash"]
    _, _, page = _call(app, qs="nets")
    assert nhash.hex().encode() in page  # the per-net input field

    # submit a guess through the form -> verified server-side
    _form(app, "nets", {nhash.hex(): PSK.decode()})
    assert core.db.q1("SELECT n_state FROM nets")["n_state"] == 1


def test_search_modes(core):
    app = make_wsgi_app(core)
    _cracked_net(core)
    h = hl.parse(tfx.make_pmkid_line(PSK, ESSID, seed="ui1"))
    mac = h.mac_ap.hex()
    # full BSSID
    _, _, page = _call(app, qs="search&search=" + mac)
    assert ESSID in page
    # OUI prefix
    _, _, page = _call(app, qs="search&search=" + mac[:6])
    assert ESSID in page
    # client MAC
    _, _, page = _call(app, qs="search&search=client:" + h.mac_sta.hex())
    assert ESSID in page
    # SSID prefix
    _, _, page = _call(app, qs="search&search=" + urllib.parse.quote("TierN"))
    assert ESSID in page
    # too short -> no table
    _, _, page = _call(app, qs="search&search=ab")
    assert ESSID not in page


def test_stats_my_nets_dicts_pages(core, tmp_path):
    from dwpa_tpu.server.jobs import maintenance

    app = make_wsgi_app(core)
    owner_key = core.create_user("o2@example.com")
    _cracked_net(core, userkey=owner_key)
    core.add_dict("dict/x.txt.gz", "x.txt.gz", "0" * 32, 42)
    maintenance(core)

    _, _, stats = _call(app, qs="stats")
    assert b"Current round ends in" in stats and b"progress" in stats
    # machine clients still get JSON
    import json
    out = {}
    env = {"REQUEST_METHOD": "GET", "QUERY_STRING": "stats",
           "wsgi.input": io.BytesIO(b""), "CONTENT_LENGTH": "0"}
    body = b"".join(app(env, lambda s, h: out.setdefault("s", s)))
    assert json.loads(body)["cracked"] >= 1

    _, _, mine = _call(app, qs="my_nets", cookie=owner_key)
    assert PSK in mine and b"Download all founds" in mine
    _, _, anon = _call(app, qs="my_nets")
    assert b"No user key set" in anon

    _, _, dicts = _call(app, qs="dicts")
    assert b"x.txt.gz" in dicts and b"42" in dicts


# -- user-key lifecycle ----------------------------------------------------


def test_key_issue_flow_new_mail(core):
    app = make_wsgi_app(core)
    status, headers, page = _form(app, "get_key", {"mail": "new@example.com"})
    assert b"User key issued" in page
    assert "key=" in headers.get("Set-Cookie", "")
    key = headers["Set-Cookie"].split("key=")[1].split(";")[0]
    assert core.user_key_exists(key)
    # the key went out by mail
    (to, subject, mail_body), = core.mailer.sent
    assert to == "new@example.com" and key in mail_body


def test_key_reset_throttled_24h(core):
    app = make_wsgi_app(core)
    _form(app, "get_key", {"mail": "reset@example.com"})
    first_key = core.mailer.sent[0][2].split(": ")[1]

    # immediate re-request: throttled, no mail
    _, _, page = _form(app, "get_key", {"mail": "reset@example.com"})
    assert b"try again tomorrow" in page
    assert len(core.mailer.sent) == 1

    # age the linkkeyts by >24h -> reset link goes out
    core.db.x("UPDATE users SET linkkeyts = linkkeyts - 90000")
    _, _, page = _form(app, "get_key", {"mail": "reset@example.com"})
    assert b"check your e-mail" in page
    assert len(core.mailer.sent) == 2
    link_mail = core.mailer.sent[1][2]
    assert "?get_key=" in link_mail
    new_key = link_mail.split("?get_key=")[1].strip()

    # old key still works until the link is followed
    assert core.user_key_exists(first_key)
    status, headers, _ = _call(app, qs="get_key=" + new_key)
    assert status.startswith("302")
    assert new_key in headers.get("Set-Cookie", "")
    assert core.user_key_exists(new_key)
    assert not core.user_key_exists(first_key)

    # a stale/bogus linkkey does not promote
    _, _, page = _call(app, qs="get_key=" + "c" * 32)
    assert b"NOT set" in page


def test_invalid_mail_rejected(core):
    app = make_wsgi_app(core)
    _, _, page = _form(app, "get_key", {"mail": "not-an-email"})
    assert b"No valid e-mail" in page
    assert core.mailer.sent == []


def test_captcha_seam_gates_issue(core):
    core.captcha = lambda resp, ip: resp == "ok"
    app = make_wsgi_app(core)
    _, _, page = _form(app, "get_key",
                       {"mail": "c@example.com", "g-recaptcha-response": "bad"})
    assert b"Captcha validation failed" in page
    _, _, page = _form(app, "get_key",
                       {"mail": "c@example.com", "g-recaptcha-response": "ok"})
    assert b"User key issued" in page


def test_cookie_set_and_remove(core):
    app = make_wsgi_app(core)
    key = core.create_user("cookie@example.com")
    status, headers, _ = _form(app, "", {"key": key})
    assert status.startswith("302") and key in headers["Set-Cookie"]
    # unknown key -> cookie cleared instead
    status, headers, _ = _form(app, "", {"key": "d" * 32})
    assert "Max-Age=0" in headers["Set-Cookie"]
    # bosskey is always accepted
    status, headers, _ = _form(app, "", {"key": BOSSKEY})
    assert BOSSKEY in headers["Set-Cookie"]
    # explicit removal
    status, headers, _ = _form(app, "", {"remkey": "1"})
    assert "Max-Age=0" in headers["Set-Cookie"]


def test_viewer_resolution(core):
    key = core.create_user("v@example.com")
    assert ui.resolve_viewer(core, BOSSKEY).tier == "boss"
    assert ui.resolve_viewer(core, key).tier == "keyed"
    assert ui.resolve_viewer(core, "").tier == "anonymous"
    assert ui.resolve_viewer(core, "zz").tier == "anonymous"
