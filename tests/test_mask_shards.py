"""Smart-keyspace scheduling + loopback execution tests (the ks vertical).

Server side: ks rows compile loudly at admin time, mask shards lease
smallest-keyspace-first with an advancing coverage frontier, releases
retire coverage under the (hkey, epoch) key, and reaped ranges re-issue
without double-credit.  Client side: a mask unit cracks a planted
in-keyspace PSK with ZERO dict bytes on the wire, and a mid-shard
restart resumes bit-identically off the ``mask_done`` checkpoint.
"""

import io
import json
import urllib.parse

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.client.main import ClientConfig, TpuCrackClient
from dwpa_tpu.client.protocol import ServerAPI
from dwpa_tpu.keyspace import KeyspaceError
from dwpa_tpu.server import Database, ServerCore, make_wsgi_app
from dwpa_tpu.server.jobs import maintenance

PSK = b"wifipass77"   # index 77 of the 100-word ^wifipass\d{2}$ keyspace
ESSID = b"MaskNet"


@pytest.fixture
def core(tmp_path):
    c = ServerCore(Database(":memory:"), dictdir=str(tmp_path / "dicts"),
                   capdir=str(tmp_path / "caps"))
    c.mask_shard_span = 40
    return c


def _plant(core, seed="ms1", psk=PSK):
    core.add_hashlines([tfx.make_pmkid_line(psk, ESSID, seed=seed)])
    core.db.x("UPDATE nets SET algo = ''")


def _masks(work):
    return [(m["mask"], m["skip"], m["limit"]) for m in work["masks"]]


# ---------------------------------------------------------------------------
# admin surface
# ---------------------------------------------------------------------------


def test_ks_add_rejects_loudly_and_inserts_nothing(core):
    with pytest.raises(KeyspaceError):
        core.ks_add(r"^Net$", r"free.*")        # uncompilable pass side
    import re
    with pytest.raises(re.error):
        core.ks_add(r"([", r"^pw\d{2}$")        # broken ssid side
    assert core.ks_rows(enabled_only=False) == []
    kid = core.ks_add(r"^Net$", r"^pw\d{2}$", priority=7)
    rows = core.ks_rows()
    assert [r["ks_id"] for r in rows] == [kid]
    assert rows[0]["priority"] == 7 and rows[0]["enabled"] == 1


# ---------------------------------------------------------------------------
# scheduling: frontier, ordering, coverage
# ---------------------------------------------------------------------------


def test_mask_shards_issue_smallest_first_with_advancing_frontier(core):
    _plant(core)
    core.ks_add(r"^MaskNet$", r"^wifipass\d{2}$|^[ab]pw-pass$")
    w1 = core.get_work(1)
    # smallest keyspace first: the 2-word [ab] branch leads
    assert _masks(w1) == [("?1pw-pass", 0, 2)]
    assert w1["dicts"] == []
    assert w1["masks"][0]["custom"] == {"1": "ab"}
    w2 = core.get_work(1)
    assert _masks(w2) == [("wifipass?d?d", 0, 40)]
    w3 = core.get_work(2)   # budget 2: the two remaining shards
    assert _masks(w3) == [("wifipass?d?d", 40, 40), ("wifipass?d?d", 80, 20)]
    assert core.get_work(4) is None   # keyspace fully in flight
    # releases retire coverage: hkey NULL, spans intact
    for w in (w1, w2, w3):
        core.put_work({"hkey": w["hkey"], "epoch": w.get("epoch"),
                       "type": "bssid", "cand": []})
    rows = core.db.q("SELECT skip, span, hkey FROM n2m ORDER BY skip, span")
    assert all(r["hkey"] is None for r in rows)
    assert sum(r["span"] for r in rows) == 102
    assert core.get_work(4) is None   # fully covered, nothing re-issues


def test_mask_shards_ride_along_with_dicts(core, tmp_path):
    import gzip
    import hashlib
    import os

    _plant(core)
    core.ks_add(r"^MaskNet$", r"^wifipass\d{2}$")
    os.makedirs(core.dictdir, exist_ok=True)
    blob = gzip.compress(b"not-the-psk\n")
    with open(os.path.join(core.dictdir, "one.txt.gz"), "wb") as f:
        f.write(blob)
    core.add_dict("dict/one.txt.gz", "one.txt.gz",
                  hashlib.md5(blob).hexdigest(), 1, rules=None)
    w = core.get_work(2)
    # budget 2 = 1 dict + 1 mask shard in the same unit
    assert len(w["dicts"]) == 1
    assert _masks(w) == [("wifipass?d?d", 0, 40)]


def test_keyspace_gauges_track_total_and_done(core, tmp_path):
    from dwpa_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    core = ServerCore(Database(":memory:"), dictdir=str(tmp_path / "d2"),
                      capdir=str(tmp_path / "c2"), registry=reg)
    core.mask_shard_span = 40
    _plant(core)
    core.ks_add(r"^MaskNet$", r"^wifipass\d{2}$")
    core.observe_metrics()
    text = reg.render_prometheus()
    assert "dwpa_keyspace_mask_total 100" in text
    assert "dwpa_keyspace_mask_done 0" in text
    w = core.get_work(1)
    core.put_work({"hkey": w["hkey"], "epoch": w.get("epoch"),
                   "type": "bssid", "cand": []})
    core.observe_metrics()
    text = reg.render_prometheus()
    assert "dwpa_keyspace_mask_total 100" in text
    assert "dwpa_keyspace_mask_done 40" in text


def test_reaped_ranges_reissue_without_double_credit(core):
    _plant(core)
    core.ks_add(r"^MaskNet$", r"^wifipass\d{2}$")
    w1 = core.get_work(1)
    assert _masks(w1) == [("wifipass?d?d", 0, 40)]
    # abandon the unit: age the lease + its coverage past the window
    core.db.x("UPDATE n2m SET ts = ts - 4 * 3600 WHERE hkey = ?",
              (w1["hkey"],))
    core.db.x("UPDATE leases SET issued = issued - 4 * 3600 WHERE hkey = ?",
              (w1["hkey"],))
    maintenance(core)
    # reap DELETEs (a NULLed row would count as completed coverage):
    # the abandoned range reopens as a gap
    assert core.db.q1("SELECT COUNT(*) c FROM n2m")["c"] == 0
    # maintenance materialized the cracked-psk feedback dict, so budget
    # 2 = that dict + the re-issued shard riding along
    w2 = core.get_work(2)
    assert _masks(w2) == [("wifipass?d?d", 0, 40)]   # same range, re-issued
    assert w2["hkey"] != w1["hkey"]
    # the stale holder's keyed release matches no live lease: no credit
    core.put_work({"hkey": w1["hkey"], "epoch": w1.get("epoch"),
                   "type": "bssid", "cand": []})
    assert core.db.q1(
        "SELECT COALESCE(SUM(span), 0) c FROM n2m WHERE hkey IS NULL"
    )["c"] == 0
    # the live holder's release credits the range exactly once
    core.put_work({"hkey": w2["hkey"], "epoch": w2.get("epoch"),
                   "type": "bssid", "cand": []})
    assert core.db.q1(
        "SELECT COALESCE(SUM(span), 0) c FROM n2m WHERE hkey IS NULL"
    )["c"] == 40


def test_cracked_net_drops_its_mask_coverage(core):
    from dwpa_tpu.models import hashline as hl

    _plant(core)
    core.ks_add(r"^MaskNet$", r"^wifipass\d{2}$")
    w = core.get_work(1)
    mac = hl.parse(w["hashes"][0]).mac_ap.hex()
    core.put_work({"hkey": w["hkey"], "epoch": w.get("epoch"),
                   "type": "bssid", "cand": [{"k": mac, "v": PSK.hex()}]})
    assert core.db.q1("SELECT n_state FROM nets")["n_state"] == 1
    assert core.db.q1("SELECT COUNT(*) c FROM n2m")["c"] == 0
    assert core.get_work(4) is None


# ---------------------------------------------------------------------------
# loopback execution: zero dict bytes, exact resume
# ---------------------------------------------------------------------------


class LoopbackAPI(ServerAPI):
    """ServerAPI whose transport is a direct WSGI call (no sockets)."""

    def __init__(self, app, **kw):
        kw.setdefault("max_tries", 1)
        kw.setdefault("sleep", lambda s: None)
        super().__init__("http://loopback/", **kw)
        self.app = app
        self.requests = []

    def fetch(self, url, data=None, max_tries=None):
        parsed = urllib.parse.urlparse(url)
        body = json.dumps(data).encode() if data is not None else b""
        environ = {
            "REQUEST_METHOD": "POST" if data is not None else "GET",
            "PATH_INFO": parsed.path or "/",
            "QUERY_STRING": parsed.query,
            "CONTENT_LENGTH": str(len(body)),
            "wsgi.input": io.BytesIO(body),
            "REMOTE_ADDR": "127.0.0.1",
        }
        out = {}

        def start_response(status, headers):
            out["status"] = status

        resp = b"".join(self.app(environ, start_response))
        self.requests.append((environ["REQUEST_METHOD"], url, len(resp)))
        if not out["status"].startswith("200"):
            raise ConnectionError(f"{url}: {out['status']}")
        return resp


def _client(core, tmp_path, **cfg_kw):
    cfg_kw.setdefault("batch_size", 64)
    cfg_kw.setdefault("dictcount", 1)
    cfg_kw.setdefault("device_streams", "off")
    cfg = ClientConfig(base_url="http://loopback/",
                       workdir=str(tmp_path / "work"), **cfg_kw)
    api = LoopbackAPI(make_wsgi_app(core))
    return TpuCrackClient(cfg, api=api, log=lambda *a, **k: None)


def _mask_core(tmp_path, span=200, psk=PSK):
    core = ServerCore(Database(":memory:"), dictdir=str(tmp_path / "dicts"),
                      capdir=str(tmp_path / "caps"))
    core.mask_shard_span = span
    _plant(core, seed="lb1", psk=psk)
    core.ks_add(r"^MaskNet$", r"^wifipass\d{2}$")
    return core


def test_mask_unit_cracks_planted_psk_with_zero_dict_bytes(tmp_path):
    core = _mask_core(tmp_path)
    client = _client(core, tmp_path)
    work = client.api.get_work(1)
    assert work["dicts"] == [] and _masks(work) == [("wifipass?d?d", 0, 100)]
    res = client.process_work(work)
    assert res.accepted and [f.psk for f in res.founds] == [PSK]
    assert core.db.q1("SELECT n_state, pass FROM nets")["pass"] == PSK
    # zero candidate bytes on the wire: no dict endpoint was ever hit
    assert [u for m, u, n in client.api.requests if "dict" in u] == []
    # the unit's coverage retired with the crack
    assert core.db.q1("SELECT COUNT(*) c FROM n2m")["c"] == 0


def test_mask_checkpoint_counts_keyspace_coordinates(tmp_path):
    """``mask_done`` advances in exact keyspace indices (block counts,
    not padded batch widths) — the coordinate the -s/-l resume relies
    on."""
    core = _mask_core(tmp_path)
    client = _client(core, tmp_path)
    snaps = []
    real = client._write_resume
    client._write_resume = lambda w: (
        snaps.append(json.loads(json.dumps(w.get("_progress")))), real(w))[1]
    work = client.api.get_work(1)
    res = client.process_work(work)
    assert res.accepted
    dones = [s["mask_done"] for s in snaps if s]
    assert dones == sorted(dones) and dones[-1] == 100
    assert 64 in dones   # the first 64-wide block checkpointed mid-shard


def test_mid_shard_restart_resumes_bit_identical(tmp_path):
    """Kill after the first mask batch: the revived client replays
    EXACTLY the uncovered suffix (no candidate re-tried, none skipped)
    and still finds the planted PSK sitting past the checkpoint."""
    core = _mask_core(tmp_path)
    crashed = _client(core, tmp_path)
    work = crashed.api.get_work(1)
    # simulated crash after one 64-wide batch: the checkpoint the client
    # would have written (dict passes fully done, mask shard at 64)
    work["_progress"] = {"done": 10 ** 6, "mask_done": 64, "cand": []}
    crashed._write_resume(work)

    revived = _client(core, tmp_path)
    replayed = revived._read_resume()
    assert replayed == work
    res = revived.process_work(replayed)
    assert res.accepted
    # bit-identical suffix: exactly keyspace - checkpoint candidates
    assert res.candidates_tried == 100 - 64
    assert [f.psk for f in res.founds] == [PSK]   # index 77 >= 64
    assert core.db.q1("SELECT n_state FROM nets")["n_state"] == 1


def test_restart_fast_forwards_whole_finished_shards(tmp_path):
    """A unit carrying several shards resumes past fully-done shards via
    the cumulative mask_done counter and mid-resumes the next one."""
    # psk in the LAST shard, so no shard short-circuits on an early
    # crack and the replayed suffix is exactly the uncovered keyspace
    core = _mask_core(tmp_path, span=40, psk=b"wifipass92")
    client = _client(core, tmp_path, dictcount=3)
    work = client.api.get_work(3)
    assert _masks(work) == [("wifipass?d?d", 0, 40), ("wifipass?d?d", 40, 40),
                            ("wifipass?d?d", 80, 20)]
    # crash at cumulative 50: shard 1 done, shard 2 at offset 10
    work["_progress"] = {"done": 10 ** 6, "mask_done": 50, "cand": []}
    res = client.process_work(work)
    assert res.accepted
    assert res.candidates_tried == 100 - 50
    assert [f.psk for f in res.founds] == [b"wifipass92"]
