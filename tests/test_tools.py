"""Ops & migration tooling (server/tools.py + the server CLI) — the
misc/ script equivalents (migrate recrack, create_gz, dedup, fill_pr,
enrich_pmkid)."""

import gzip
import hashlib
import json
import os

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.server import tools
from dwpa_tpu.server.__main__ import main as cli_main
from dwpa_tpu.server.capture import extract_hashlines
from dwpa_tpu.server.core import ServerCore
from dwpa_tpu.server.db import Database

PSK = b"ops-battery-1"
ESSID = b"OpsNet"


@pytest.fixture
def core(tmp_path):
    db = Database(":memory:")
    return ServerCore(db, dictdir=str(tmp_path / "dicts"), capdir=str(tmp_path / "caps"))


def _crack_one(core, psk=PSK, essid=ESSID, seed="t1"):
    line = tfx.make_eapol_line(psk, essid, keyver=2, seed=seed)
    core.add_hashlines([line])
    net = core.db.q1("SELECT * FROM nets ORDER BY net_id DESC")
    assert core.put_work(
        {"hkey": "0" * 32,
         "cand": [{"k": net["struct"].split("*")[3], "v": psk.hex()}]}
    )
    return core.db.q1("SELECT * FROM nets WHERE net_id = ?", (net["net_id"],))


# ---------------------------------------------------------------------------
# recrack_verify (migrate_to_m22000.php:121-141)


def test_recrack_verify_passes_on_good_data(core):
    row = _crack_one(core)
    assert row["n_state"] == 1
    assert tools.recrack_verify(core) == {"checked": 1}


def test_recrack_verify_aborts_on_corruption(core):
    row = _crack_one(core)
    core.db.x("UPDATE nets SET pass = ? WHERE net_id = ?",
              (b"wrong-pass-99", row["net_id"]))
    with pytest.raises(tools.RecrackError):
        tools.recrack_verify(core)


def test_recrack_verify_detects_pmk_mismatch(core):
    row = _crack_one(core)
    core.db.x("UPDATE nets SET pmk = ? WHERE net_id = ?",
              (b"\x13" * 32, row["net_id"]))
    with pytest.raises(tools.RecrackError):
        tools.recrack_verify(core)


# ---------------------------------------------------------------------------
# pack_dict (create_gz.sh)


def test_pack_dict_deterministic_and_registered(core, tmp_path):
    words = [b"password", b"letmein99", b"hunter22"]
    out1 = tools.pack_dict(core, words, "mini")
    path = os.path.join(core.dictdir, "mini.txt.gz")
    with gzip.open(path, "rb") as f:
        assert f.read() == b"".join(w + b"\n" for w in words)
    with open(path, "rb") as f:
        assert hashlib.md5(f.read()).hexdigest() == out1["dhash"]
    row = core.db.q1("SELECT * FROM dicts WHERE dname = 'mini.txt.gz'")
    assert row["wcount"] == 3 and row["dhash"] == out1["dhash"]
    # determinism: same content -> same dhash (no mtime in the header)
    out2 = tools.pack_dict(core, words, "mini")
    assert out2["dhash"] == out1["dhash"]


def test_pack_dict_from_plain_file(core, tmp_path):
    src = tmp_path / "src.txt"
    src.write_bytes(b"alpha-key\n\nbeta-key-2\n")
    out = tools.pack_dict(core, str(src), "fromfile")
    assert out["wcount"] == 2  # blank line dropped


# ---------------------------------------------------------------------------
# dedup_dicts (dedup.sh)


def test_dedup_dicts_earlier_wins_and_sorts(core, tmp_path):
    a = tmp_path / "a.txt.gz"
    b = tmp_path / "b.txt.gz"
    tools._write_gz(str(a), [b"shared-word", b"alpha-only"])
    tools._write_gz(str(b), [b"zzz-long-word-here", b"shared-word", b"bb-word"])
    stats = tools.dedup_dicts([str(a), str(b)])
    assert stats[str(a)] == {"before": 2, "after": 2}
    assert stats[str(b)] == {"before": 3, "after": 2}
    with gzip.open(str(b), "rb") as f:
        kept = f.read().splitlines()
    # shared word dropped; remainder shortest-first
    assert kept == [b"bb-word", b"zzz-long-word-here"]


def test_dedup_dicts_refreshes_dict_rows(core, tmp_path):
    out = tools.pack_dict(core, [b"one-word-1", b"two-word-2"], "first")
    out2 = tools.pack_dict(core, [b"two-word-2", b"three-word"], "second")
    p1 = os.path.join(core.dictdir, "first.txt.gz")
    p2 = os.path.join(core.dictdir, "second.txt.gz")
    tools.dedup_dicts([p1, p2], core=core)
    row = core.db.q1("SELECT * FROM dicts WHERE dname = 'second.txt.gz'")
    assert row["wcount"] == 1
    assert row["dhash"] != out2["dhash"]


# ---------------------------------------------------------------------------
# fill_pr / enrich_message_pair (fill_pr.php / enrich_pmkid.php)


def test_fill_pr_backfills_probes(core):
    blob, _ = tfx.make_handshake_capture(
        PSK, ESSID, seed="pr1", probes=(b"CoffeeShop", b"airport-free")
    )
    s_id = core.add_submission(blob)
    # legacy-style ingest: hashlines only, probes never harvested
    lines, _probes = extract_hashlines(blob)
    core.add_hashlines(lines, s_id=s_id)
    assert core.db.q1("SELECT COUNT(*) c FROM prs")["c"] == 0
    out = tools.fill_pr(core)
    assert out["submissions"] == 1 and out["probes"] == 2
    assert core.db.q1("SELECT COUNT(*) c FROM prs")["c"] == 2
    # idempotent
    assert tools.fill_pr(core)["submissions"] == 1
    assert core.db.q1("SELECT COUNT(*) c FROM prs")["c"] == 2


def test_enrich_message_pair_backfills_nulls(core):
    blob, _ = tfx.make_handshake_capture(PSK, ESSID, seed="en1", with_pmkid=False)
    s_id = core.add_submission(blob)
    lines, _ = extract_hashlines(blob)
    core.add_hashlines(lines, s_id=s_id)
    # simulate a legacy row migrated without message-pair info
    core.db.x("UPDATE nets SET message_pair = NULL")
    out = tools.enrich_message_pair(core)
    assert out["updated"] == 1
    row = core.db.q1("SELECT message_pair, struct FROM nets")
    assert row["message_pair"] is not None
    assert row["struct"] == lines[0]


# ---------------------------------------------------------------------------
# CLI plumbing


def test_cli_pack_and_recrack(tmp_path, capsys):
    db = str(tmp_path / "wpa.db")
    src = tmp_path / "w.txt"
    src.write_bytes(b"cli-word-01\ncli-word-02\n")
    cli_main(["pack-dict", "--db", db, str(src), "--name", "cli",
              "--dictdir", str(tmp_path / "d")])
    out = json.loads(capsys.readouterr().out)
    assert out["wcount"] == 2
    cli_main(["recrack", "--db", db])
    assert json.loads(capsys.readouterr().out) == {"checked": 0}


def test_cli_jobs_once(tmp_path, capsys):
    db = str(tmp_path / "wpa.db")
    cli_main(["jobs", "--db", db])
    out = json.loads(capsys.readouterr().out)
    assert "maintenance" in out and "keygen" in out


# ---------------------------------------------------------------------------
# psk_lookup (3wifi.php equivalent) and conf-file loading


def test_psk_lookup_submits_through_verification(core):
    line = tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="pl1")
    core.add_hashlines([line])
    net = core.db.q1("SELECT * FROM nets")
    from dwpa_tpu.server.db import long2mac
    from dwpa_tpu.server.jobs import psk_lookup

    mac = long2mac(net["bssid"])
    calls = []

    def lookup(macs):
        calls.append(macs)
        # external DB knows this PSK plus a wrong one that must not stick
        return {m: (PSK if m == mac else b"garbage-psk") for m in macs}

    out = psk_lookup(core, lookup)
    assert out == {"queried": 1, "submitted": 1}
    row = core.db.q1("SELECT n_state, pass FROM nets")
    assert row["n_state"] == 1 and row["pass"] == PSK
    # queried flag set -> not asked again
    assert psk_lookup(core, lookup) == {"queried": 0, "submitted": 0}
    assert calls == [[mac]]


def test_psk_lookup_rejects_wrong_answers(core):
    core.add_hashlines([tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="pl2")])
    from dwpa_tpu.server.jobs import psk_lookup

    out = psk_lookup(core, lambda macs: {m: b"wrong-psk-111" for m in macs})
    assert out["submitted"] == 1
    # the claim failed independent re-verification; net stays uncracked
    assert core.db.q1("SELECT n_state FROM nets")["n_state"] == 0


def test_cli_conf_file(tmp_path, capsys):
    conf = tmp_path / "conf.json"
    conf.write_text(json.dumps({
        "db": str(tmp_path / "conf.db"),
        "dictdir": str(tmp_path / "cd"),
    }))
    cli_main(["recrack", "--conf", str(conf)])
    assert json.loads(capsys.readouterr().out) == {"checked": 0}


def test_cli_requires_db_or_conf(tmp_path):
    with pytest.raises(SystemExit):
        cli_main(["recrack"])


# ---------------------------------------------------------------------------
# legacy-storage migration (misc/migrate_to_m22000.php:253-270)


def _hccapx_from_line(line: str) -> bytes:
    """Pack a parsed m22000 EAPOL line back into a 393-byte hccapx record
    (the hashcat v4 struct the reference migrates FROM)."""
    from dwpa_tpu.models import hashline as hl

    h = hl.parse(line)
    keyver = h.keyver
    rec = bytearray(393)
    rec[0:4] = b"HCPX"
    rec[4:8] = (4).to_bytes(4, "little")       # version
    rec[8] = h.message_pair or 0
    rec[9] = len(h.essid)
    rec[10:10 + len(h.essid)] = h.essid
    rec[42] = keyver
    rec[43:59] = h.pmkid_or_mic
    rec[59:65] = h.mac_ap
    rec[65:97] = h.anonce
    rec[97:103] = h.mac_sta
    rec[103:135] = h.eapol[17:49]              # snonce from the EAPOL body
    rec[135:137] = len(h.eapol).to_bytes(2, "little")
    rec[137:137 + len(h.eapol)] = h.eapol
    return bytes(rec)


def test_convert_legacy_hccapx_roundtrips_crackable(core):
    src = tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="mig1")
    line = tools.convert_legacy(_hccapx_from_line(src))
    from dwpa_tpu.models import hashline as hl
    from dwpa_tpu.oracle import m22000 as oracle

    h = hl.parse(line)
    assert h.essid == ESSID and h.hash_type == hl.TYPE_EAPOL
    assert oracle.check_key_m22000(h, [PSK]) is not None


def test_convert_legacy_pmkid_line(core):
    src = tfx.make_pmkid_line(PSK, ESSID, seed="mig2")
    p = src.split("*")
    legacy = ":".join([p[2], p[3], p[4], p[5]])
    line = tools.convert_legacy(legacy)
    from dwpa_tpu.models import hashline as hl
    from dwpa_tpu.oracle import m22000 as oracle

    h = hl.parse(line)
    assert h.hash_type == hl.TYPE_PMKID
    assert oracle.check_key_m22000(h, [PSK]) is not None


def test_convert_legacy_rejects_junk():
    assert tools.convert_legacy(b"not a record") is None
    assert tools.convert_legacy(b"a:b") is None


def test_migrate_legacy_ingests_and_recracks(core):
    eap = tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="mig3")
    pmk = tfx.make_pmkid_line(PSK, ESSID, seed="mig4")
    p = pmk.split("*")
    records = [
        _hccapx_from_line(eap),
        ":".join([p[2], p[3], p[4], p[5]]).encode(),
        b"garbage line",
    ]
    res = tools.migrate_legacy(core, records)
    assert res["converted"] == 2 and res["unconvertible"] == 1
    assert res["new"] == 2
    assert core.db.q1("SELECT COUNT(*) c FROM nets")["c"] == 2
    # migrated nets crack through the normal acceptance path
    for net in core.db.q("SELECT * FROM nets"):
        assert core.put_work(
            {"hkey": "0" * 32,
             "cand": [{"k": net["struct"].split("*")[3], "v": PSK.hex()}]}
        )
    tools.recrack_verify(core)


def test_cli_migrate(tmp_path, capsys):
    dbp = str(tmp_path / "m.db")
    eap = tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="mig5")
    hx = tmp_path / "old.hccapx"
    hx.write_bytes(_hccapx_from_line(eap))
    cli_main(["migrate", "--db", dbp, str(hx)])
    out = json.loads(capsys.readouterr().out)
    assert out["new"] == 1


def test_cli_jobs_with_offline_lookups(tmp_path, capsys):
    dbp = str(tmp_path / "j.db")
    db = Database(dbp)
    core2 = ServerCore(db)
    line = tfx.make_pmkid_line(PSK, ESSID, seed="jl1")
    core2.add_hashlines([line])
    mac = line.split("*")[3]
    (tmp_path / "geo.json").write_text(
        json.dumps({mac: {"lat": 1.5, "lon": 2.5, "country": "BG"}})
    )
    (tmp_path / "psk.txt").write_bytes(b"%s:%s\n" % (mac.encode(), PSK))
    db.close()
    cli_main(["jobs", "--db", dbp,
              "--geo-file", str(tmp_path / "geo.json"),
              "--psk-file", str(tmp_path / "psk.txt")])
    out = json.loads(capsys.readouterr().out)
    assert out["geolocate"] == 1
    assert out["psk_lookup"]["submitted"] == 1
    db = Database(dbp)
    net = db.q1("SELECT n_state, pass FROM nets")
    assert net["n_state"] == 1 and net["pass"] == PSK
    geo = db.q1("SELECT lat, country FROM bssids")
    assert geo["lat"] == 1.5 and geo["country"] == "BG"


# ---------------------------------------------------------------------------
# client distribution artifacts (web/hc/, help_crack.py:158-189)


def test_pack_client_builds_runnable_zipapp(tmp_path):
    import subprocess
    import sys

    out = tools.pack_client(str(tmp_path / "hc"))
    assert out["files"] > 20
    manifest = (tmp_path / "hc" / "dwpa_tpu.version").read_text().split()
    assert manifest[0] == out["version"] and manifest[1] == out["md5"]
    assert hashlib.md5((tmp_path / "hc" / "dwpa_tpu.pyz").read_bytes()
                       ).hexdigest() == out["md5"]
    # the archive actually runs as a client entry point
    r = subprocess.run(
        [sys.executable, str(tmp_path / "hc" / "dwpa_tpu.pyz"), "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0 and "dwpa" in r.stdout


def test_pack_client_deterministic(tmp_path):
    a = tools.pack_client(str(tmp_path / "a"))
    b = tools.pack_client(str(tmp_path / "b"))
    assert a["md5"] == b["md5"]


def test_update_flow_against_packed_client(tmp_path):
    """End-to-end self-update probe: a server with an hcdir serving a
    NEWER packed client makes check_update download + verify it."""
    import io
    import json as _json
    import urllib.parse

    from dwpa_tpu.client.main import ClientConfig, TpuCrackClient
    from dwpa_tpu.client.protocol import ServerAPI
    from dwpa_tpu.server.api import make_wsgi_app

    hcdir = str(tmp_path / "hc")
    out = tools.pack_client(hcdir, version="999.0.0")
    db = Database(":memory:")
    core2 = ServerCore(db, hcdir=hcdir)
    app = make_wsgi_app(core2)

    class API(ServerAPI):
        def fetch(self, url, data=None, max_tries=None):
            parsed = urllib.parse.urlparse(url)
            env = {"REQUEST_METHOD": "GET", "PATH_INFO": parsed.path or "/",
                   "QUERY_STRING": parsed.query, "CONTENT_LENGTH": "0",
                   "wsgi.input": io.BytesIO(b""), "REMOTE_ADDR": "1.2.3.4"}
            st = {}
            body = b"".join(app(env, lambda s, h: st.update(status=s)))
            if not st["status"].startswith("200"):
                raise ConnectionError(st["status"])
            return body

        def remote_version(self):
            return self.fetch("http://x/hc/dwpa_tpu.version").decode().strip()

    cfg = ClientConfig(base_url="http://x/", workdir=str(tmp_path / "w"))
    client = TpuCrackClient(cfg, api=API("http://x/"), log=lambda *a: None)
    assert client.check_update()
    pyz = os.path.join(cfg.workdir, "dwpa_tpu-999.0.0.pyz")
    assert hashlib.md5(open(pyz, "rb").read()).hexdigest() == out["md5"]


def test_pack_client_rejects_bad_version(tmp_path):
    with pytest.raises(ValueError, match="rejected"):
        tools.pack_client(str(tmp_path / "hc"), version="v2.0-rc1")


def test_cli_pack_client_reads_conf(tmp_path, capsys):
    conf = tmp_path / "conf.json"
    conf.write_text(json.dumps({"hcdir": str(tmp_path / "hc")}))
    cli_main(["pack-client", "--conf", str(conf)])
    out = json.loads(capsys.readouterr().out)
    assert os.path.isfile(tmp_path / "hc" / "dwpa_tpu.version")
    assert out["files"] > 20


def test_serve_with_jobs_rejects_memory_db():
    with pytest.raises(SystemExit, match="file-backed"):
        cli_main(["serve", "--db", ":memory:", "--with-jobs"])


def test_materializer_thread_stops_and_joins(core):
    """Thread-lifecycle audit: the serve-mode queue materializer must be
    stoppable (stop event honored within one tick) and joinable — no
    orphan ``dwpa-queue-materializer`` thread after shutdown."""
    import threading

    from dwpa_tpu.server.__main__ import _start_materializer

    before = set(threading.enumerate())
    started = _start_materializer(core, interval=0.05)
    assert started is not None
    thread, stop = started
    assert thread.name == "dwpa-queue-materializer"
    assert thread.is_alive()
    stop.set()
    thread.join(5.0)
    assert not thread.is_alive()
    assert set(threading.enumerate()) == before

    # Queue disabled (--no-work-queue): no thread to manage at all.
    core.queue = None
    assert _start_materializer(core) is None
