"""Test env: force an 8-device virtual CPU platform.

Mirrors how the driver validates multi-chip sharding: a
``jax.sharding.Mesh`` over 8 virtual CPU devices stands in for a TPU pod
slice.  The container's sitecustomize registers the axon TPU plugin and
overrides ``jax_platforms`` in every interpreter (jax is already imported
before pytest starts), so setting env vars is not enough — the config must
be updated after import, before any backend is initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, jax.devices()
