"""Test env: force an 8-device virtual CPU platform before jax imports.

Mirrors how the driver validates multi-chip sharding: a
``jax.sharding.Mesh`` over 8 virtual CPU devices stands in for a TPU pod
slice.  Must run before any test module imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
