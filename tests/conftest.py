"""Test env: force an 8-device virtual CPU platform.

Mirrors how the driver validates multi-chip sharding: a
``jax.sharding.Mesh`` over 8 virtual CPU devices stands in for a TPU pod
slice.  The container's sitecustomize registers the axon TPU plugin and
overrides ``jax_platforms`` in every interpreter (jax is already imported
before pytest starts), so setting env vars is not enough — the config must
be updated after import, before any backend is initialized.
"""

import os

# DWPA_TEST_TPU=1 keeps the native platform so device-only tests (e.g. the
# full-4096 Pallas bit-exactness check) can run against the real chip.
if os.environ.get("DWPA_TEST_TPU") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) == 8, jax.devices()

# Persist XLA compilations across suite runs: the heavyweight shard_map
# steps dominate suite wall-clock and their HLO is identical run-to-run.
from dwpa_tpu.utils.compcache import enable_compilation_cache

enable_compilation_cache(
    os.path.join(os.path.dirname(__file__), "..", ".pytest_xla_cache")
)

# Recompilation sentinel (dwpa_tpu.analysis): guards steady-state sweeps
# against per-batch XLA recompiles.  Imported AFTER the platform setup
# above — the plugin pulls in jax.
from dwpa_tpu.analysis.pytest_plugin import (  # noqa: E402,F401
    lock_witness, recompile_sentinel)


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'` (ROADMAP.md); soak tests opt out with
    # this marker instead of living outside the tree
    config.addinivalue_line(
        "markers", "slow: long-running soak tests excluded from tier-1")
