"""dwpa_tpu.obs unit tests: registry semantics (types, labels, merge,
Prometheus rendering), span nesting + the device-sync hook, and the
logging config (console format preserved; DWPA_LOG=json structured).
"""

import io
import json
import logging

import pytest

from dwpa_tpu.obs import (MetricsRegistry, SpanTracer, allgather_json,
                          default_registry, is_emitter, merged_slice_snapshot,
                          setup_logging)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    c = r.counter("dwpa_t_total", "things")
    c.inc()
    c.labels(kind="a").inc(3)
    assert r.value("dwpa_t_total") == 1
    assert r.value("dwpa_t_total", kind="a") == 3

    g = r.gauge("dwpa_t_gauge")
    g.set(5)
    g.dec(2)
    assert r.value("dwpa_t_gauge") == 3
    with pytest.raises(TypeError):
        c.set(1)  # counters don't set
    with pytest.raises(TypeError):
        g.observe(1)  # gauges don't observe

    h = r.histogram("dwpa_t_seconds", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5)
    h.observe(50)
    snap = r.snapshot()["dwpa_t_seconds"]["samples"][0]
    assert snap["count"] == 3 and snap["sum"] == 55.5
    assert snap["buckets"] == [1, 1, 1]  # per-bound + overflow


def test_family_registration_idempotent_but_type_strict():
    r = MetricsRegistry()
    a = r.counter("dwpa_t_total", "first help wins")
    b = r.counter("dwpa_t_total", "ignored")
    assert a is b
    with pytest.raises(ValueError):
        r.gauge("dwpa_t_total")


def test_prometheus_rendering_escapes_and_cumulates():
    r = MetricsRegistry()
    r.counter("dwpa_t_total", 'help with \\ and\nnewline').labels(
        q='va"l\nue').inc()
    h = r.histogram("dwpa_t_seconds", buckets=(1.0,))
    h.observe(0.5)
    h.observe(2.0)
    text = r.render_prometheus()
    assert '# HELP dwpa_t_total help with \\\\ and\\nnewline' in text
    assert 'dwpa_t_total{q="va\\"l\\nue"} 1' in text
    # cumulative buckets: le="1" holds 1, +Inf holds all 2
    assert 'dwpa_t_seconds_bucket{le="1"} 1' in text
    assert 'dwpa_t_seconds_bucket{le="+Inf"} 2' in text
    assert 'dwpa_t_seconds_count 2' in text
    assert json.loads(r.render_json())  # JSON form parses


def test_snapshot_merge_sums_everything():
    a, b = MetricsRegistry(), MetricsRegistry()
    for r, n in ((a, 2), (b, 5)):
        r.counter("dwpa_t_total").inc(n)
        r.gauge("dwpa_t_pmks").labels(**{"pass": "2"}).set(n * 100)
        r.histogram("dwpa_t_seconds", buckets=(1.0,)).observe(n)
    merged = MetricsRegistry()
    merged.merge_snapshot(a.snapshot())
    merged.merge_snapshot(b.snapshot())
    assert merged.value("dwpa_t_total") == 7
    # additive gauges SUM: per-host PMK/s -> slice PMK/s
    assert merged.value("dwpa_t_pmks", **{"pass": "2"}) == 700
    hist = merged.snapshot()["dwpa_t_seconds"]["samples"][0]
    assert hist["count"] == 2 and hist["sum"] == 7


def test_merge_rejects_mismatched_buckets():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("dwpa_t_seconds", buckets=(1.0,)).observe(0.5)
    b.histogram("dwpa_t_seconds", buckets=(2.0,)).observe(0.5)
    m = MetricsRegistry()
    m.merge_snapshot(a.snapshot())
    with pytest.raises(ValueError, match="bucket bounds"):
        m.merge_snapshot(b.snapshot())


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_spans_nest_and_record_histogram():
    r = MetricsRegistry()
    t = SpanTracer(r)
    with t.span("outer"):
        with t.span("inner"):
            pass
    inner, outer = t.records()
    assert (inner["name"], inner["parent"], inner["depth"]) == \
        ("inner", "outer", 1)
    assert (outer["name"], outer["parent"], outer["depth"]) == \
        ("outer", None, 0)
    assert outer["t0"] <= inner["t0"] <= inner["t1"] <= outer["t1"]
    assert r.value("dwpa_span_seconds", span="inner") == 1


def test_span_stack_recovers_from_abandoned_child():
    """An exception that skips a child's stop must not wedge the stack:
    stopping the parent discards the abandoned child."""
    t = SpanTracer(MetricsRegistry())
    outer = t.start("outer")
    t.start("abandoned")  # never stopped
    outer.stop()
    with t.span("after") as sp:
        pass
    assert sp.depth == 0  # stack fully unwound
    names = [x["name"] for x in t.records()]
    assert names == ["outer", "after"]


def test_span_stop_idempotent_and_sync_callable_runs_before_clock():
    t = SpanTracer(MetricsRegistry())
    ran = []
    sp = t.start("s")
    sp.stop(sync=lambda: ran.append(1))
    first = sp.seconds
    assert ran == [1]
    assert sp.stop() == first  # second stop: no re-record
    assert len(t.records("s")) == 1


# ---------------------------------------------------------------------------
# logging
# ---------------------------------------------------------------------------


def test_setup_logging_plain_preserves_console_format(monkeypatch):
    monkeypatch.delenv("DWPA_LOG", raising=False)
    buf = io.StringIO()
    logger = setup_logging(stream=buf, force=True)
    try:
        logging.getLogger("dwpa_tpu.client").info("challenge: passed")
        assert buf.getvalue() == "challenge: passed\n"
    finally:
        setup_logging(force=True)  # restore a default handler


def test_setup_logging_json_lines(monkeypatch):
    monkeypatch.setenv("DWPA_LOG", "json")
    buf = io.StringIO()
    setup_logging(stream=buf, force=True)
    try:
        logging.getLogger("dwpa_tpu.server.jobs").warning("tick failed")
        rec = json.loads(buf.getvalue())
        assert rec["level"] == "WARNING"
        assert rec["logger"] == "dwpa_tpu.server.jobs"
        assert rec["msg"] == "tick failed"
        assert rec["ts"].endswith("Z")
    finally:
        monkeypatch.delenv("DWPA_LOG")
        setup_logging(force=True)


def test_setup_logging_idempotent():
    a = setup_logging()
    n = len(a.handlers)
    b = setup_logging()
    assert a is b and len(b.handlers) == n


# ---------------------------------------------------------------------------
# multi-host plumbing (single-process paths; the collective forms ride
# the same process_allgather contract tests/test_multihost.py exercises)
# ---------------------------------------------------------------------------


def test_single_process_allgather_and_emitter():
    assert is_emitter()
    assert allgather_json({"a": 1}) == [{"a": 1}]


def test_merged_slice_snapshot_single_process():
    r = MetricsRegistry()
    r.gauge("dwpa_client_pmk_per_s").labels(**{"pass": "2"}).set(123.0)
    merged = merged_slice_snapshot(r)
    assert merged.value("dwpa_client_pmk_per_s", **{"pass": "2"}) == 123.0


def test_default_registry_is_shared():
    assert default_registry() is default_registry()
