"""Loopback client <-> server integration tests.

Runs TpuCrackClient against make_wsgi_app entirely in-process: a
ServerAPI whose ``fetch`` invokes the WSGI app directly, so the complete
reference flow (help_crack.py:881-957) — challenge gate, get_work, dict
download + md5 check, two-pass crack, put_work, resume replay, autotune —
is exercised over the exact wire protocol with no sockets.
"""

import gzip
import hashlib
import io
import json
import os
import urllib.parse

import jax
import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.client.main import ClientConfig, TpuCrackClient
from dwpa_tpu.client.protocol import NoNets, ServerAPI, VersionRejected
from dwpa_tpu.models import hashline as hl
from dwpa_tpu.obs import MetricsRegistry
from dwpa_tpu.server import Database, ServerCore, make_wsgi_app

PSK = b"loopback-psk1"
ESSID = b"LoopbackNet"


class LoopbackAPI(ServerAPI):
    """ServerAPI whose transport is a direct WSGI call (no sockets)."""

    def __init__(self, app, **kw):
        kw.setdefault("max_tries", 1)
        kw.setdefault("sleep", lambda s: None)
        super().__init__("http://loopback/", **kw)
        self.app = app
        self.requests = []

    def fetch(self, url: str, data: dict = None, max_tries: int = None) -> bytes:
        parsed = urllib.parse.urlparse(url)
        body = json.dumps(data).encode() if data is not None else b""
        environ = {
            "REQUEST_METHOD": "POST" if data is not None else "GET",
            "PATH_INFO": parsed.path or "/",
            "QUERY_STRING": parsed.query,
            "CONTENT_LENGTH": str(len(body)),
            "wsgi.input": io.BytesIO(body),
            "REMOTE_ADDR": "127.0.0.1",
        }
        out = {}

        def start_response(status, headers):
            out["status"] = status

        resp = b"".join(self.app(environ, start_response))
        self.requests.append((environ["REQUEST_METHOD"], url))
        if not out["status"].startswith("200"):
            raise ConnectionError(f"{url}: {out['status']}")
        return resp


@pytest.fixture
def server(tmp_path):
    db = Database(":memory:")
    core = ServerCore(db, dictdir=str(tmp_path / "dicts"), capdir=str(tmp_path / "caps"))
    return core


def _add_dict(core, words, name="loop.txt.gz"):
    os.makedirs(core.dictdir, exist_ok=True)
    blob = gzip.compress(b"\n".join(words) + b"\n")
    path = os.path.join(core.dictdir, name)
    with open(path, "wb") as f:
        f.write(blob)
    dhash = hashlib.md5(blob).hexdigest()
    core.add_dict(f"dict/{name}", name, dhash, len(words), rules=None)
    return path, dhash


def _ingest(core, lines):
    core.add_hashlines(lines)
    core.db.x("UPDATE nets SET algo = ''")  # release to volunteers


def _client(server, tmp_path, registry=None, **cfg_kw):
    cfg_kw.setdefault("batch_size", 64)
    cfg_kw.setdefault("dictcount", 1)
    # Lockstep by default: on the forced-8-device 1-core test host the
    # stream path trades one fused 8-way execution for 8 serialized
    # single-device ones, several times slower at these toy batch
    # sizes.  test_metrics_after_one_work_unit opts back in ("auto")
    # and carries the stream-path assertions for the whole file.
    cfg_kw.setdefault("device_streams", "off")
    cfg = ClientConfig(base_url="http://loopback/",
                       workdir=str(tmp_path / "work"), **cfg_kw)
    api = LoopbackAPI(make_wsgi_app(server))
    return TpuCrackClient(cfg, api=api, log=lambda *a, **k: None,
                          registry=registry)


def test_full_round_trip(server, tmp_path):
    """get_work -> crack -> put_work: the net ends cracked server-side,
    the potfile records the found, and the lease is closed."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="rt1"),
                     tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="rt2")])
    _add_dict(server, [b"nope-000001", PSK, b"nope-000002"])
    client = _client(server, tmp_path)

    assert client.challenge()
    work = client.api.get_work(client.dictcount)
    assert len(work["hashes"]) == 2  # same-SSID nets grouped into one unit
    res = client.process_work(work)

    assert res.accepted
    assert sorted(f.psk for f in res.founds) == [PSK, PSK]
    rows = server.db.q("SELECT n_state, pass FROM nets")
    assert all(r["n_state"] == 1 and r["pass"] == PSK for r in rows)
    assert server.db.q1("SELECT COUNT(*) c FROM n2d WHERE hkey IS NOT NULL")["c"] == 0
    # potfile written, resume cleared
    pot = open(client.potfile).read()
    assert PSK.decode() in pot
    assert not os.path.exists(client.resume_path)


def test_run_loop_with_challenge_gate(server, tmp_path):
    """client.run(): challenge gate passes, one unit processed end-to-end."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="rl1")])
    _add_dict(server, [PSK])
    client = _client(server, tmp_path, max_work_units=1)
    assert client.run() == 1
    assert server.db.q1("SELECT n_state FROM nets")["n_state"] == 1


def test_resume_replay_after_crash(server, tmp_path):
    """A resume snapshot from a crashed session is replayed instead of
    fetching new work (help_crack.py:745-763)."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="rr1")])
    _add_dict(server, [PSK])
    crashed = _client(server, tmp_path)
    work = crashed.api.get_work(1)
    crashed._write_resume(work)  # simulated crash: resume left behind

    revived = _client(server, tmp_path)

    def fail_get_work(dictcount):
        raise AssertionError("must replay the resume, not fetch new work")

    revived.api.get_work = fail_get_work
    revived.cfg.max_work_units = 1
    # the challenge still gates a resumed session
    assert revived.challenge()
    replayed = revived._read_resume()
    assert replayed == work
    res = revived.process_work(replayed)
    assert res.accepted
    assert not os.path.exists(revived.resume_path)


def test_corrupt_resume_discarded(server, tmp_path):
    client = _client(server, tmp_path)
    with open(client.resume_path, "w") as f:
        f.write("{not json")
    assert client._read_resume() is None
    assert not os.path.exists(client.resume_path)


def test_resume_rejected_on_batch_size_change(server, tmp_path):
    """A resume written under a different -b must restart the unit, not
    skip-by-count: the batch size changes crack_rules' chunk boundaries,
    so the old done counter indexes a DIFFERENT candidate order and a
    replay would silently skip candidates that were never tried."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="bs1")])
    _add_dict(server, [PSK])
    crashed = _client(server, tmp_path, batch_size=64)
    work = crashed.api.get_work(1)
    work["_progress"] = {"done": 37, "cand": []}  # mid-unit checkpoint
    crashed._write_resume(work)
    assert work["_batch"] == 64  # stamped alongside _ver/_nproc

    # same build, same topology, different -b: the snapshot is discarded
    revived = _client(server, tmp_path, batch_size=32)
    assert revived._read_resume() is None
    assert not os.path.exists(revived.resume_path)

    # unchanged -b still replays (the stamp must not over-reject)
    crashed._write_resume(work)
    same = _client(server, tmp_path, batch_size=64)
    assert same._read_resume() == work


def test_metrics_after_one_work_unit(server, tmp_path):
    """Telemetry contract for one loopback unit (the ISSUE-2 acceptance
    check): transport counters for get_work/put_work, a nonzero PMK/s
    gauge, the autotune/dictcount instruments, and well-nested spans."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="mt1")])
    _add_dict(server, [b"filler-000001", PSK, b"filler-000002"])
    reg = MetricsRegistry()
    client = _client(server, tmp_path, registry=reg, device_streams="auto")
    # streams on under the 8-device single-process test mesh; a real
    # single-chip host (DWPA_TEST_TPU=1) legitimately stays lockstep
    use_streams = client._use_streams()
    assert use_streams == (jax.local_device_count() > 1
                           and jax.process_count() == 1)

    work = client.api.get_work(client.dictcount)
    res = client.process_work(work)
    assert res.accepted

    # transport counters: one get_work, one put_work, one dict download
    assert reg.value("dwpa_client_requests_total", endpoint="get_work") == 1
    assert reg.value("dwpa_client_requests_total", endpoint="put_work") == 1
    assert reg.value("dwpa_client_requests_total",
                     endpoint="dict_download") == 1
    # engine throughput: pass 2 carried the dict, so its PMK/s gauge is
    # live and positive (pass 1 may be too fast to register)
    assert reg.value("dwpa_client_pmk_per_s", **{"pass": "2"}) > 0
    # unit accounting + autotune: a sub-second unit tunes dictcount up
    assert reg.value("dwpa_client_work_units_total", accepted="true") == 1
    assert reg.value("dwpa_client_founds_total") == 1
    assert reg.value("dwpa_client_autotune_total", direction="up") == 1
    assert reg.value("dwpa_client_dictcount") == 2
    # no resume, no recompile-counter surprises recorded as gauges
    assert reg.value("dwpa_client_resume_skipped_total") is None

    # resilience telemetry (ISSUE-10): the retry/backoff/circuit/outbox
    # families are registered up front — present in the scrape even on a
    # fault-free run — the circuit rests CLOSED, and the unit's found
    # flowed through the outbox (journaled before put_work, then acked)
    from dwpa_tpu.client.protocol import CircuitBreaker

    assert reg.value("dwpa_client_circuit_state") == CircuitBreaker.CLOSED
    assert reg.value("dwpa_outbox_pending_total") == 1
    assert reg.value("dwpa_outbox_acked_total") == 1
    assert client.outbox.pending_count() == 0
    assert reg.series("dwpa_client_retries_total") == {}  # clean transport
    scrape = reg.render_prometheus()
    for fam in ("dwpa_client_retries_total", "dwpa_client_backoff_seconds",
                "dwpa_client_circuit_state", "dwpa_outbox_pending_total",
                "dwpa_outbox_acked_total"):
        assert fam in scrape, fam

    # spans: the work_unit span parents pass1/pass2/dict_download/
    # put_work, and every child interval nests inside it
    recs = client.tracer.records()
    by_name = {r["name"]: r for r in recs}
    for name in ("work_unit", "pass1", "pass2", "put_work",
                 "dict_download", "get_work"):
        assert name in by_name, (name, sorted(by_name))
    unit = by_name["work_unit"]
    for name, parent in (("pass1", "work_unit"), ("pass2", "work_unit"),
                         ("put_work", "work_unit"),
                         # the lazy dict fetch fires when pass 2 first
                         # pulls its stream, so it nests under pass2
                         ("dict_download", "pass2")):
        child = by_name[name]
        assert child["parent"] == parent, child
        assert unit["t0"] <= child["t0"] <= child["t1"] <= unit["t1"], child
    assert by_name["get_work"]["parent"] is None
    # span durations also land in the registry histogram
    assert reg.value("dwpa_span_seconds", span="work_unit") == 1

    # candidate-feed telemetry (ISSUE-3): both passes consumed from the
    # feed, so the dwpa_feed_* family is live per pass, block counts are
    # positive, and the candidate counters cover the unit's stream
    for feed_name in ("pass1", "pass2"):
        assert reg.value("dwpa_feed_blocks_total", feed=feed_name) >= 1, \
            feed_name
        assert reg.value("dwpa_feed_consumer_starve_seconds",
                         feed=feed_name) >= 1
    fed = sum(reg.series("dwpa_feed_candidates_total").values())
    assert fed >= res.candidates_tried
    assert reg.value("dwpa_span_seconds", span="feed:produce") >= 2

    # device-stream telemetry (ISSUE-8): the 8-device single-process
    # test mesh turns streams on by default, so every pass ran as
    # per-device streams — blocks land in the per-device counter and
    # the stream spans are traced alongside the pass spans
    if use_streams:
        stream_blocks = reg.series("dwpa_stream_blocks_total")
        assert stream_blocks and sum(stream_blocks.values()) >= 2  # 2 passes
        for labels, busy in reg.series("dwpa_stream_busy_fraction").items():
            assert 0.0 <= busy <= 1.0, (labels, busy)
        for labels, depth in reg.series("dwpa_stream_queue_depth").items():
            assert depth >= 0, (labels, depth)
        assert {"stream:dispatch", "stream:collect"} <= \
            {r["name"] for r in recs}


def test_pmkstore_metrics_and_warm_unit(server, tmp_path):
    """PMK-store loopback contract (the ISSUE-4 acceptance check): with
    --pmk-cache-dir set, one work unit surfaces the dwpa_pmkstore_*
    metric set in the registry (and so in the ?metrics scrape rendering),
    and a REPLAY of the same unit serves its candidates from the cache —
    hits recorded, the PSK still cracked through cached PMKs."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="pm1")])
    _add_dict(server, [b"cacheable-%06d" % i for i in range(30)] + [PSK])
    reg = MetricsRegistry()
    client = _client(server, tmp_path, registry=reg,
                     pmk_cache_dir=str(tmp_path / "pmkcache"))

    work = client.api.get_work(client.dictcount)
    res = client.process_work(dict(work))
    assert res.accepted and [f.psk for f in res.founds] == [PSK]
    # cold unit: the dwpa_pmkstore_* family is live — misses counted,
    # every derived PMK written back, names present in the scrape form
    assert reg.value("dwpa_pmkstore_misses_total") > 0
    assert reg.value("dwpa_pmkstore_writes_total") > 0
    text = reg.render_prometheus()
    for name in ("dwpa_pmkstore_hits_total", "dwpa_pmkstore_misses_total",
                 "dwpa_pmkstore_writes_total", "dwpa_pmkstore_bytes",
                 "dwpa_pmkstore_hit_ratio"):
        assert name in text, name

    # warm replay of the same unit (server-side state reset): the dict
    # stream repeats, so pass 2 runs on cache hits
    server.db.x("UPDATE nets SET n_state = 0, pass = NULL, algo = ''")
    hits_before = reg.value("dwpa_pmkstore_hits_total") or 0
    res2 = client.process_work(dict(work))
    assert res2.accepted and [f.psk for f in res2.founds] == [PSK]
    assert reg.value("dwpa_pmkstore_hits_total") > hits_before
    assert 0 < reg.value("dwpa_pmkstore_hit_ratio") <= 1


def test_dictcache_metrics_and_warm_unit(server, tmp_path):
    """Packed-dict-cache loopback contract (the ISSUE-9 acceptance
    check): with --dict-cache-dir set, the first unit cold-streams the
    dict while writing the packed cache (misses counted, bytes on
    disk), and a REPLAY of the same unit serves pass 2 from mmap'd
    packed blocks — hits recorded, the warm words/s gauge live, the PSK
    still cracked with the identical found list."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="dc1")])
    _add_dict(server, [b"cacheable-%06d" % i for i in range(30)] + [PSK])
    reg = MetricsRegistry()
    client = _client(server, tmp_path, registry=reg,
                     dict_cache_dir=str(tmp_path / "dictcache"))

    work = client.api.get_work(client.dictcount)
    res = client.process_work(dict(work))
    assert res.accepted and [f.psk for f in res.founds] == [PSK]
    # cold unit: blocks streamed past the cache, the entry committed
    assert reg.value("dwpa_dictcache_miss_blocks_total") > 0
    assert not reg.value("dwpa_dictcache_hit_blocks_total")
    assert reg.value("dwpa_dictcache_bytes") > 0
    text = reg.render_prometheus()
    for name in ("dwpa_dictcache_hit_blocks_total",
                 "dwpa_dictcache_miss_blocks_total",
                 "dwpa_dictcache_bytes", "dwpa_dictcache_words_per_s"):
        assert name in text, name

    # warm replay of the same unit (server-side state reset): pass 2
    # now serves pre-packed blocks, zero gunzip, zero re-packing
    server.db.x("UPDATE nets SET n_state = 0, pass = NULL, algo = ''")
    misses_before = reg.value("dwpa_dictcache_miss_blocks_total")
    res2 = client.process_work(dict(work))
    assert res2.accepted and [f.psk for f in res2.founds] == [PSK]
    assert reg.value("dwpa_dictcache_hit_blocks_total") > 0
    assert reg.value("dwpa_dictcache_miss_blocks_total") == misses_before
    assert reg.value("dwpa_dictcache_words_per_s", feed="warm") > 0


def test_rules_metrics_in_loopback_unit(server, tmp_path):
    """Mesh-aggregate feed telemetry contract (the ISSUE-11 acceptance
    check): a rules unit surfaces the device-expansion counters — every
    (word, rule) pair lands in EXACTLY one of
    dwpa_rules_device_expanded_total or
    dwpa_rules_host_fallback_total{reason="purge"|"overflow"} — and the
    rules:expand span is traced inside pass 2."""
    mangled = b"METRICWORD9!"  # 'metricword9!' through 'u'
    _ingest(server, [tfx.make_pmkid_line(mangled, ESSID, seed="rm1")])
    words = [b"metricword9!", b"metricfill-1", b"metricfill-2", b"y" * 70]
    os.makedirs(server.dictdir, exist_ok=True)
    blob = gzip.compress(b"\n".join(words) + b"\n")
    open(os.path.join(server.dictdir, "rm.txt.gz"), "wb").write(blob)
    # ':' and 'u' expand on device; '@a' purges on the host interpreter
    server.add_dict("dict/rm.txt.gz", "rm.txt.gz",
                    hashlib.md5(blob).hexdigest(), len(words),
                    rules=":\nu\n@a\n")
    reg = MetricsRegistry()
    client = _client(server, tmp_path, registry=reg)

    work = client.api.get_work(1)
    res = client.process_work(work)
    assert res.accepted and [f.psk for f in res.founds] == [mangled]

    # 3 eligible bases x 2 device rules; 3 x 1 purge rule host-applied;
    # the 70-byte base falls back to the host for ALL 3 rules
    dev = reg.value("dwpa_rules_device_expanded_total")
    purge = reg.value("dwpa_rules_host_fallback_total", reason="purge")
    over = reg.value("dwpa_rules_host_fallback_total", reason="overflow")
    assert dev == 3 * 2
    assert purge == 3 * 1
    assert over == 1 * 3
    # conservation: the split is a partition of the expanded keyspace
    assert dev + purge + over == len(words) * 3
    for name in ("dwpa_rules_device_expanded_total",
                 "dwpa_rules_host_fallback_total"):
        assert name in reg.render_prometheus(), name

    # the expansion span fires inside the pass2 interval
    recs = client.tracer.records()
    spans = [r for r in recs if r["name"] == "rules:expand"]
    assert spans
    p2 = next(r for r in recs if r["name"] == "pass2")
    assert all(p2["t0"] <= s["t0"] <= s["t1"] <= p2["t1"] for s in spans)


def test_potfile_fsync_per_found(server, tmp_path, monkeypatch):
    """Potfile appends are flushed AND fsynced per found: a crash right
    after put_work must not lose the only local copy of a cracked PSK
    to the page cache."""
    import dwpa_tpu.client.main as cm

    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(cm.os, "fsync",
                        lambda fd: (synced.append(fd), real_fsync(fd))[1])
    client = _client(server, tmp_path)

    class _Line:
        raw = "WPA*01*fsync-test"

    class _Found:
        line = _Line()
        psk = b"fsyncpsk1"

    client._record_founds([_Found(), _Found()])
    assert len(synced) == 2
    pot = open(client.potfile).read()
    assert pot.count("fsyncpsk1") == 2


def test_outbox_exactly_once_after_kill_before_put_work(server, tmp_path):
    """Kill between crack and put_work (ISSUE-10): the found is journaled
    in the outbox before the first submission attempt, a restarted client
    delivers it exactly once, and a resume-replay re-crack of the same
    unit never double-submits the acked key."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="xo1")])
    _add_dict(server, [PSK])
    client = _client(server, tmp_path)
    work = client.api.get_work(1)

    def killed(hkey, cand, max_tries=None, epoch=None):
        raise ConnectionError("killed between crack and put_work")

    client.api.put_work = killed
    res = client.process_work(work)
    assert not res.accepted and [f.psk for f in res.founds] == [PSK]
    assert client.outbox.pending_count() == 1  # journaled, not lost
    assert server.db.q1(
        "SELECT COUNT(*) c FROM nets WHERE n_state = 1")["c"] == 0

    # "restart": a fresh client over the same workdir replays the journal
    # and the startup drain delivers the found exactly once.
    revived = _client(server, tmp_path)
    assert revived.outbox.pending_count() == 1
    revived._drain_outbox()
    assert revived.outbox.pending_count() == 0
    rows = server.db.q("SELECT n_state, pass FROM nets")
    assert [(r["n_state"], r["pass"]) for r in rows] == [(1, PSK)]

    # The resume file survived the crash too: replaying the unit
    # re-cracks the same PSK, but record() drops the acked key so the
    # server never sees a second submission.
    puts = []
    real_put = revived.api.put_work
    revived.api.put_work = lambda hkey, cand, max_tries=None, epoch=None: (
        puts.append(list(cand))
        or real_put(hkey, cand, max_tries=max_tries, epoch=epoch))
    res2 = revived.process_work(dict(work))
    assert res2.accepted
    assert puts == []  # all founds already acked: no put_work at all


def test_shard_word_blocks_covers_stream_in_lockstep():
    """The no-rules pass-2 slicer (multi-host): per block, the hosts'
    shards partition the global stream in order, every host yields the
    SAME number of same-sized batches (padding, not absence, for short
    tails — the SPMD-lockstep contract), and the reported global counts
    sum to the stream length."""
    from dwpa_tpu.client.main import shard_word_blocks

    words = [b"w%05d" % i for i in range(2 * 3 * 16 + 11)]  # ragged tail
    nproc, bs = 3, 16
    per_host = [list(shard_word_blocks(words, nproc, pid, bs))
                for pid in range(nproc)]
    # identical block structure on every host
    nblocks = {len(h) for h in per_host}
    assert nblocks == {len(per_host[0])}
    for blocks in zip(*per_host):
        sizes = {len(mine) for mine, _ in blocks}
        gcounts = {g for _, g in blocks}
        assert len(sizes) == 1 and len(gcounts) == 1  # lockstep
    # concatenating the hosts' shards per block (padding stripped)
    # reconstructs the global stream exactly once, in order
    rebuilt = []
    for blocks in zip(*per_host):
        for mine, _ in blocks:
            rebuilt.extend(w for w in mine if w != b"")
    assert rebuilt == words
    assert sum(g for _, g in per_host[0]) == len(words)
    # full blocks shard to exactly batch_size per host
    assert all(len(mine) == bs for mine, _ in per_host[1][:-1])


def test_dict_md5_mismatch_rejected(server, tmp_path):
    """A corrupted dict download fails the md5 gate (help_crack.py:533-534)."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="md5-1")])
    path, dhash = _add_dict(server, [PSK])
    with open(path, "ab") as f:
        f.write(b"corruption\n")  # server file changes after registration
    client = _client(server, tmp_path)
    work = client.api.get_work(1)
    with pytest.raises(ValueError, match="md5 mismatch"):
        client._fetch_dicts(work)


def test_autotune_moves_dictcount(server, tmp_path):
    client = _client(server, tmp_path)
    client.cfg.pace_target = 1e9  # everything is "fast"
    for _ in range(20):
        client._autotune(elapsed=1.0)
    assert client.dictcount == 15  # clamped at the reference cap
    client.cfg.pace_target = 0.0  # everything is "slow"
    for _ in range(20):
        client._autotune(elapsed=1.0)
    assert client.dictcount == 1


def test_challenge_gate_failure_exits(server, tmp_path, monkeypatch):
    """A cracker that cannot reproduce the known PSK must not fetch work
    (help_crack.py:886-895)."""
    client = _client(server, tmp_path)

    class BrokenEngine:
        def __init__(self, *a, **k):
            self.groups = {}

        def crack(self, words):
            return []

    import dwpa_tpu.client.main as cm

    monkeypatch.setattr(cm, "M22000Engine", BrokenEngine)
    with pytest.raises(SystemExit):
        client.run()


def test_version_gate_and_no_nets(server, tmp_path):
    app = make_wsgi_app(server)
    old = LoopbackAPI(app, hc_ver="2.0.0")
    with pytest.raises(VersionRejected):
        old.get_work(1)
    empty = LoopbackAPI(app)
    with pytest.raises(NoNets):
        empty.get_work(1)


def test_prdict_pass1_candidates(server, tmp_path):
    """The dynamic PROBEREQUEST dict feeds pass 1 (help_crack.py:557-568):
    a PSK present only as a probed SSID in the same capture still cracks
    the unit even though no server dict contains it."""
    probed_psk = b"ProbedNetwork1"
    # One capture: the handshake's PSK is also some station's probed SSID.
    blob, _ = tfx.make_handshake_capture(probed_psk, ESSID, probes=[probed_psk])
    from dwpa_tpu.server.api import submit_capture

    submit_capture(server, blob)
    server.db.x("UPDATE nets SET algo = ''")
    _add_dict(server, [b"filler-word-1"])  # server dict does NOT contain it

    client = _client(server, tmp_path)
    work = client.api.get_work(1)
    assert work.get("prdict") is True
    res = client.process_work(work)
    assert any(f.psk == probed_psk for f in res.founds)
    rows = server.db.q("SELECT n_state, pass FROM nets")
    assert all(r["n_state"] == 1 and r["pass"] == probed_psk for r in rows)


def test_intra_unit_checkpoint_written(server, tmp_path, monkeypatch):
    """_progress (done counter + founds) is checkpointed after every
    completed batch — the hashcat --session analog (SURVEY.md §5.4)."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="ck1")])
    words = [b"filler-%06d" % i for i in range(40)] + [PSK]
    _add_dict(server, words)
    client = _client(server, tmp_path, batch_size=16)
    snapshots = []
    real_write = client._write_resume
    monkeypatch.setattr(
        client, "_write_resume",
        lambda work: (snapshots.append(json.loads(json.dumps(work))),
                      real_write(work))[1],
    )
    work = client.api.get_work(client.dictcount)
    res = client.process_work(work)
    assert res.accepted and [f.psk for f in res.founds] == [PSK]
    dones = [s["_progress"]["done"] for s in snapshots if "_progress" in s]
    assert dones and dones == sorted(dones) and dones[-1] >= len(words)
    # the found PSK was checkpointed before put_work
    assert any(s["_progress"]["cand"] for s in snapshots if "_progress" in s)


def test_resume_skips_done_and_resubmits_founds(server, tmp_path):
    """A resumed unit skips the completed prefix and re-submits prior
    founds (which may not have reached the server before the crash)."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="ck2")])
    net = server.db.q1("SELECT bssid FROM nets")
    from dwpa_tpu.server.db import long2mac
    mac = long2mac(net["bssid"])
    # dict whose PSK sits inside the "already done" prefix
    _add_dict(server, [PSK] + [b"filler-%06d" % i for i in range(40)])
    client = _client(server, tmp_path, batch_size=16)
    work = client.api.get_work(client.dictcount)
    work["_progress"] = {
        "done": 10 ** 6,  # far past the whole stream: nothing re-tried
        "cand": [{"k": mac.hex(), "v": PSK.hex()}],
    }
    res = client.process_work(work)
    assert res.candidates_tried == 0 and res.founds == []
    assert res.accepted
    row = server.db.q1("SELECT n_state, pass FROM nets")
    assert row["n_state"] == 1 and row["pass"] == PSK


def test_cracked_dict_runs_in_pass1_with_rkg(server, tmp_path):
    """A work unit carrying cracked.txt.gz: the client streams it (plus
    the server's rkg.txt.gz) through the work rules in pass 1 and cracks
    a net whose PSK only appears in the rkg dictionary
    (help_crack.py:469-509)."""
    from dwpa_tpu.server.jobs import regen_rkg_dict

    _ingest(server, [tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="cd1")])
    # the vendor-key dict holds the PSK; the cracked dict holds chaff
    server.add_hashlines([tfx.make_pmkid_line(PSK, b"OtherNet", seed="cd1v")])
    server.db.x(
        "UPDATE nets SET algo = 'Vendor', n_state = 1, pass = ? "
        "WHERE ssid = ?", (PSK, b"OtherNet"))
    regen_rkg_dict(server, os.path.join(server.dictdir, "rkg.txt.gz"))
    _add_dict(server, [b"chaff-00001", b"chaff-00002"], name="cracked.txt.gz")

    client = _client(server, tmp_path)
    work = client.api.get_work(client.dictcount)
    assert any("cracked.txt.gz" in d["dpath"] for d in work["dicts"])
    res = client.process_work(work)
    assert [f.psk for f in res.founds] == [PSK]
    assert server.db.q1(
        "SELECT n_state FROM nets WHERE ssid = ?", (ESSID,))["n_state"] == 1


def test_cracked_dict_refresh_cadence(server, tmp_path):
    """cracked.txt.gz is re-downloaded only every cracked_refresh units
    (DAW dl_count, help_crack.py:524-529)."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="cd2")])
    _add_dict(server, [PSK], name="cracked.txt.gz")
    client = _client(server, tmp_path, cracked_refresh=3)
    work = client.api.get_work(client.dictcount)

    def dl_count():
        return sum(1 for m, u in client.api.requests if "cracked.txt.gz" in u)

    list(client._cracked_candidates(dict(work), []))  # first use: downloads
    assert dl_count() == 1
    list(client._cracked_candidates(dict(work), []))  # countdown=2: cached
    list(client._cracked_candidates(dict(work), []))  # countdown=1: cached
    assert dl_count() == 1
    list(client._cracked_candidates(dict(work), []))  # countdown=0: refresh
    assert dl_count() == 2


def test_archive_logs_appended(server, tmp_path):
    """archive.22000 / archive.res audit logs accumulate one entry per
    unit (DAW, help_crack.py:453-456,741-743)."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="ar1")])
    _add_dict(server, [PSK])
    client = _client(server, tmp_path, max_work_units=1)
    assert client.run() == 1
    arc = open(os.path.join(client.cfg.workdir, "archive.22000")).read()
    assert arc.count("WPA*") >= 1
    res_lines = open(os.path.join(client.cfg.workdir, "archive.res")).read()
    assert json.loads(res_lines.splitlines()[-1])["hkey"]


def test_rules_unit_runs_on_device_path(server, tmp_path, monkeypatch):
    """Pass 2 of a rules work unit goes through the device-expansion
    seam (crack_rules_blocks / crack_rules_streams — the hashcat-on-GPU
    analog of the reference client's ``-S -r`` invocation,
    help_crack.py:773), NOT host expansion: apply_rules must never see
    the pass-2 dict stream, and the legacy flat crack_rules entry is
    reserved for multi-host slices."""
    import dwpa_tpu.client.main as cm
    from dwpa_tpu.models.m22000 import M22000Engine as Eng
    from dwpa_tpu.rules import wpa_rules_text

    mangled = b"Devword77!1"  # 'devword77!' through 'c $1'
    _ingest(server, [tfx.make_pmkid_line(mangled, ESSID, seed="dv1")])
    os.makedirs(server.dictdir, exist_ok=True)
    blob = gzip.compress(b"devword77!\n")
    path = os.path.join(server.dictdir, "dv.txt.gz")
    open(path, "wb").write(blob)
    server.add_dict("dict/dv.txt.gz", "dv.txt.gz",
                    hashlib.md5(blob).hexdigest(), 1, rules=wpa_rules_text())

    calls = []
    real_blocks = Eng.crack_rules_blocks
    real_streams = Eng.crack_rules_streams
    monkeypatch.setattr(
        Eng, "crack_rules_blocks",
        lambda self, *a, **k: (calls.append(k.get("skip", 0)),
                               real_blocks(self, *a, **k))[1])
    monkeypatch.setattr(
        Eng, "crack_rules_streams",
        lambda self, *a, **k: (calls.append(k.get("skip", 0)),
                               real_streams(self, *a, **k))[1])
    monkeypatch.setattr(
        Eng, "crack_rules",
        lambda self, *a, **k: (_ for _ in ()).throw(
            AssertionError("single-process pass 2 must dispatch through "
                           "the blocks/streams seam")))
    monkeypatch.setattr(
        cm, "apply_rules",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("pass 2 must not host-expand rules")))

    client = _client(server, tmp_path)
    work = client.api.get_work(1)
    res = client.process_work(work)
    assert calls == [0]  # device path used, fresh unit -> no skip
    assert [f.psk for f in res.founds] == [mangled]
    assert server.db.q1("SELECT n_state FROM nets")["n_state"] == 1


def test_rules_unit_resume_mid_pass2(server, tmp_path):
    """A crash mid-pass-2 of a rules unit resumes through crack_rules'
    skip: the completed prefix is not re-reported, the PSK (reachable
    only via a device rule late in pass 2) still cracks, and the unit
    submits."""
    from dwpa_tpu.rules import parse_rule

    base = [b"resume%04dw" % i for i in range(90)]
    psk = parse_rule("u").apply(base[85])
    _ingest(server, [tfx.make_pmkid_line(psk, ESSID, seed="rm1")])
    os.makedirs(server.dictdir, exist_ok=True)
    blob = gzip.compress(b"\n".join(base) + b"\n")
    path = os.path.join(server.dictdir, "rm.txt.gz")
    open(path, "wb").write(blob)
    server.add_dict("dict/rm.txt.gz", "rm.txt.gz",
                    hashlib.md5(blob).hexdigest(), len(base),
                    rules="u\n$Z")

    first = _client(server, tmp_path, batch_size=32)
    work = first.api.get_work(1)
    res1 = first.process_work(dict(work))
    assert [f.psk for f in res1.founds] == [psk]
    total = res1.candidates_tried

    # Replay the unit as a crash at ~40% of the stream: pass 1 is empty
    # (no targeted hit material beyond generators), so most of the skip
    # lands inside crack_rules.
    server.db.x("UPDATE nets SET n_state = 0, pass = NULL, algo = ''")
    skip = int(total * 0.4)
    resumed = _client(server, tmp_path / "second", batch_size=32)
    work2 = dict(work)
    work2["_progress"] = {"done": skip, "cand": []}
    res2 = resumed.process_work(work2)
    assert [f.psk for f in res2.founds] == [psk]
    assert res2.accepted
    # reported remainder never exceeds the unskipped tail (at-least-once
    # may re-TRY a straddling sub-batch, but never re-COUNT it)
    assert 0 < res2.candidates_tried <= total - skip
    assert server.db.q1("SELECT n_state FROM nets")["n_state"] == 1


def test_client_cli_multihost_flags():
    """The CLI exposes the slice-join knobs (INSTALL.md multi-host
    recipe) without touching single-process defaults."""
    from dwpa_tpu.client.__main__ import build_parser

    a = build_parser().parse_args(["http://s/"])
    assert not a.multihost and a.coordinator is None
    a = build_parser().parse_args(["http://s/", "--multihost"])
    assert a.multihost
    a = build_parser().parse_args(
        ["http://s/", "--coordinator", "h0:8476",
         "--num-processes", "2", "--process-id", "1"])
    assert (a.coordinator, a.num_processes, a.process_id) == ("h0:8476", 2, 1)
    # a partial manual-cluster spec is a usage error, not a deep JAX
    # traceback (and never a silently-ignored flag)
    from dwpa_tpu.client.__main__ import main as cli_main

    for argv in (["http://s/", "--coordinator", "h0:8476"],
                 ["http://s/", "--num-processes", "2", "--process-id", "1"]):
        with pytest.raises(SystemExit) as e:
            cli_main(argv)
        assert e.value.code == 2  # argparse usage error


def test_fused_metrics_in_scrape_and_executor_wiring(server, tmp_path):
    """Unit-fusion telemetry contract: the dwpa_fused_* family and the
    engine-retry counter are registered up front (names visible in the
    ?metrics scrape before any fused batch runs), and fused_executor()
    binds the client's knobs/registry/tracer/store."""
    reg = MetricsRegistry()
    client = _client(server, tmp_path, registry=reg,
                     unit_queue=3, fuse_max_units=4)
    text = reg.render_prometheus()
    for name in ("dwpa_fused_units_per_batch", "dwpa_fused_fill_fraction",
                 "dwpa_unit_queue_depth", "dwpa_client_engine_retries_total"):
        assert name in text, name
    ex = client.fused_executor([])
    assert ex.batch_size == client.cfg.batch_size
    assert ex.unit_queue == 3 and ex.fuse_max_units == 4
    assert ex.registry is reg and ex.tracer is client.tracer
    assert ex.pmk_store is client.pmk_store


def test_engine_error_recovery_halves_batch(server, tmp_path):
    """In-process engine recovery: a crack dispatch that raises is
    retried once at half the batch — with the _progress checkpoint
    dropped first, since skip-by-count is unsound across a batch-size
    change — and the unit completes without touching the retry loop."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="er1")])
    _add_dict(server, [PSK])
    reg = MetricsRegistry()
    client = _client(server, tmp_path, registry=reg)
    work = client.api.get_work(1)
    work["_progress"] = {"done": 0, "cand": []}
    seen = []
    real = client.process_work

    def flaky(w):
        seen.append((client.cfg.batch_size, "_progress" in w))
        if len(seen) == 1:
            raise RuntimeError("injected XLA OOM")
        return real(w)

    client.process_work = flaky
    res = client._process_with_recovery(work)
    assert res is not None and res.accepted
    assert seen == [(64, True), (32, False)]
    assert client.cfg.batch_size == 64  # restored after the retry
    assert reg.value("dwpa_client_engine_retries_total") == 1


def test_engine_error_persistent_requeues_then_abandons(server, tmp_path):
    """Both recovery attempts failing requeues the unit with backoff via
    the resume file; ENGINE_RETRY_LIMIT total attempts abandon it."""
    _ingest(server, [tfx.make_pmkid_line(PSK, ESSID, seed="er2")])
    _add_dict(server, [PSK])
    client = _client(server, tmp_path)
    slept = []
    client.api.sleep = slept.append
    work = client.api.get_work(1)

    def boom(w):
        raise RuntimeError("device fell off the bus")

    client.process_work = boom
    assert client._process_with_recovery(work) is None
    assert work["_attempts"] == 1
    assert client.cfg.batch_size == 64  # restored before the resume stamp
    assert slept == [client.api.backoff]
    assert client._read_resume() == work  # requeued for the next loop pass
    assert client._process_with_recovery(work) is None
    assert client._process_with_recovery(work) is None
    assert work["_attempts"] == client.ENGINE_RETRY_LIMIT
    assert len(slept) == 2  # the abandoning attempt does not back off
    assert client._read_resume() is None  # abandoned, not wedged


def test_bundled_wpa_rules_crack_mangled_psk(server, tmp_path):
    """A dict packed with the bundled WPA ruleset cracks a PSK that is a
    base word through a rule ('c $1'), end-to-end over the wire — the
    bestWPA.rule distribution flow (get_work.php:84-92)."""
    from dwpa_tpu.rules import wpa_rules_text

    mangled = b"Loopword9!1"  # 'loopword9!' through 'c $1'
    _ingest(server, [tfx.make_pmkid_line(mangled, ESSID, seed="wr1")])
    os.makedirs(server.dictdir, exist_ok=True)
    blob = gzip.compress(b"loopword9!\n")
    path = os.path.join(server.dictdir, "wr.txt.gz")
    open(path, "wb").write(blob)
    server.add_dict("dict/wr.txt.gz", "wr.txt.gz",
                    hashlib.md5(blob).hexdigest(), 1,
                    rules=wpa_rules_text())
    client = _client(server, tmp_path)
    work = client.api.get_work(1)
    assert work.get("rules")  # merged + base64'd into the unit
    res = client.process_work(work)
    assert [f.psk for f in res.founds] == [mangled]
    assert server.db.q1("SELECT n_state FROM nets")["n_state"] == 1
