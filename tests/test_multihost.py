"""True multi-process mesh validation (SURVEY §5.8).

Launches two worker processes that join one jax.distributed cluster
(4 virtual CPU devices each -> an 8-device global dp mesh — standing in
for two TPU hosts of one slice), each feeding its host-local candidate
shard through ``shard_candidates``'s multi-process branch.  The planted
PSK lives on process 1, so process 0 only sees the hit through the
cross-host psum — the collective the whole multi-host design rides on.
"""

import gzip
import hashlib
import os
import socket
import subprocess
import sys
import threading

import jaxlib
import pytest

WORKER = os.path.join(os.path.dirname(__file__), "mh_worker.py")
CLIENT_WORKER = os.path.join(os.path.dirname(__file__), "mh_client_worker.py")

# The jax-0.4.37-era CPU gloo transport (jaxlib <= 0.4.37) rejects the
# shard_map collectives these tests drive with `op.preamble.length <=
# op.nbytes` (upstream transport bug, fixed in later jaxlib releases).
# The tests are environment-blocked, not wrong: xfail ONLY on those
# jaxlibs so tier-1 is deterministic here and the tests re-arm
# automatically on upgrade.  non-strict: the bug is a transport race,
# so the processes can occasionally complete anyway.
_JAXLIB_VER = tuple(int(x) for x in jaxlib.__version__.split(".")[:3])
GLOO_XFAIL = pytest.mark.xfail(
    _JAXLIB_VER <= (0, 4, 37),
    reason=f"jaxlib {jaxlib.__version__} gloo transport bug "
           "(op.preamble.length <= op.nbytes) breaks two-process "
           "shard_map collectives on CPU",
    strict=False,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _communicate_all(procs, timeout):
    """communicate() on every worker, killing ALL of them if any hangs:
    a collective desync (the bug class these tests exist to catch) parks
    the workers in a jax collective forever — they must not outlive the
    test holding CPUs and the coordinator port."""
    try:
        return [p.communicate(timeout=timeout) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=30)
        raise


@GLOO_XFAIL
def test_two_process_mesh_crack_step():
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), port],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = _communicate_all(procs, timeout=480)
    assert all(p.returncode == 0 for p in procs), \
        [(p.returncode, o[1][-800:]) for p, o in zip(procs, outs)]
    outs = [o[0] for o in outs]
    for pid, out in enumerate(outs):
        assert f"RESULT {pid} hits=1" in out, (pid, out)
        # the planted find decodes on BOTH hosts — including process 0,
        # which never held the candidate bytes (ADVICE r2: the find path
        # must work when the hit lives on a non-addressable shard)
        assert f"ENGINE {pid} finds=1 psk=multihost99 pruned=True" in out, \
            (pid, out)
        # mask path: the hit word is materialized from the global
        # keyspace column on both hosts (no candidate exchange)
        assert f"MASK {pid} finds=1 psk=12345607" in out, (pid, out)
        # partial final batch: in-window word found, padding column
        # beyond the limit never reported
        assert f"MASKPART {pid} finds=12345605" in out, (pid, out)
        # an all-invalid shard on one host must not desync the slice:
        # the other host's find still lands on both
        assert f"PAD {pid} finds=1 psk=padlock-psk7" in out, (pid, out)
        # device-rules across processes: the 'u' find (process 1's rows)
        # decodes from the replicated bitmask on both hosts, and the
        # host-tail '@b' find (process 0's block) crosses hosts through
        # the candidate exchange
        assert f"RULES {pid} finds=RULEBASE19X,rulease02x" in out, (pid, out)
        # every verify kind (PMKID + keyver 1/2/3) through the mixed
        # group assembly, each find decoded cross-host
        assert f"MIXED {pid} finds=4 keyvers=1,2,3,100" in out, (pid, out)
        # more owned hits than the per-round exchange cap: two
        # fixed-shape candidate-exchange rounds, no hit dropped
        assert f"DENSE {pid} finds=1 psk=densepsk77 rounds=2" in out, \
            (pid, out)


def test_mixed_version_slice_refuses_to_start(tmp_path):
    """A slice whose hosts run different client builds must exit with a
    clear error on EVERY host before any work — stream order is
    version-dependent, so proceeding would desync the collectives."""
    coord = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, CLIENT_WORKER, str(pid), coord, "1",
             str(tmp_path)] + (["0.0.0-mixed"] if pid == 1 else []),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = _communicate_all(procs, timeout=240)
    for pid, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode != 0, (pid, out, err)
        assert "mixed client versions" in err, (pid, err[-800:])


@GLOO_XFAIL
def test_two_process_client_single_volunteer(tmp_path):
    """The full CLIENT as one multi-host volunteer: a real socket server
    in this process, two client processes spanning one jax.distributed
    mesh.  Process 0 makes every server call exactly once (update probe,
    get_work, put_work); process 1 receives the unit only through the
    client's broadcast layer; the PSK is reachable only via a device
    rule, so pass 2 runs the sharded fused rules step across both
    hosts' devices — and the net ends cracked server-side."""
    from wsgiref.simple_server import WSGIServer, make_server
    import socketserver

    from dwpa_tpu import testing as tfx
    from dwpa_tpu.rules import parse_rule
    from dwpa_tpu.server import Database, ServerCore, make_wsgi_app

    core = ServerCore(Database(str(tmp_path / "wpa.db")),
                      dictdir=str(tmp_path / "dicts"),
                      capdir=str(tmp_path / "caps"))
    os.makedirs(core.dictdir, exist_ok=True)
    base = [b"mhcword%03d" % i for i in range(40)]
    psk = parse_rule("u").apply(base[23])  # only a device rule reaches it
    core.add_hashlines([tfx.make_pmkid_line(psk, b"MhcNet", seed="mhc")])
    blob = gzip.compress(b"\n".join(base) + b"\n")
    path = os.path.join(core.dictdir, "mhc.txt.gz")
    open(path, "wb").write(blob)
    core.add_dict("dict/mhc.txt.gz", "mhc.txt.gz",
                  hashlib.md5(blob).hexdigest(), len(base), rules="u\n$Z")
    core.db.x("UPDATE nets SET algo = ''")

    hits = {"get_work": 0, "put_work": 0}
    app = make_wsgi_app(core)

    def counting_app(environ, start_response):
        q = environ.get("QUERY_STRING", "")
        for k in hits:
            if k in q:
                hits[k] += 1
        return app(environ, start_response)

    class TS(socketserver.ThreadingMixIn, WSGIServer):
        daemon_threads = True

    srv = make_server("127.0.0.1", 0, counting_app, server_class=TS)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        coord = str(_free_port())
        procs = [
            subprocess.Popen(
                [sys.executable, CLIENT_WORKER, str(pid), coord,
                 str(srv.server_address[1]), str(tmp_path)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            for pid in (0, 1)
        ]
        outs = _communicate_all(procs, timeout=540)
    finally:
        srv.shutdown()
    assert all(p.returncode == 0 for p in procs), \
        [(p.returncode, o[1][-1500:]) for p, o in zip(procs, outs)]
    for pid, (out, _err) in enumerate(outs):
        assert f"MHCLIENT {pid} done=1 pot=yes" in out, (pid, out)
    row = core.db.q1("SELECT n_state, pass FROM nets")
    assert row["n_state"] == 1 and row["pass"] == psk
    # one volunteer, one conversation: process 0 only
    assert hits == {"get_work": 1, "put_work": 1}, hits
