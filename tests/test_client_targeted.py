"""Targeted-attack table, self-update, and per-stage timing — the DAW
client parity features (help_crack.py:615-687, :158-189; SURVEY.md §5.1)."""

import itertools
import os

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.client import targeted as tg
from dwpa_tpu.client.main import ClientConfig, TpuCrackClient, version_tuple

from test_client_loopback import LoopbackAPI, _add_dict, _client, _ingest, server  # noqa: F401


# ---------------------------------------------------------------------------
# targeted table


def test_netgear_family_shape():
    family, gen = tg.targeted_for_essid(b"NETGEAR57")
    assert family == "netgear"
    first = list(itertools.islice(gen, 3))
    assert first == [b"ancientapple000", b"ancientapple001", b"ancientapple002"]


def test_phome_family_prefix():
    family, gen = tg.targeted_for_essid(b"PLDTHOMEDSL")
    assert family == "phome"
    assert next(iter(gen)) == b"PLDTWIFI00000"


def test_imei_family_shape():
    family, gen = tg.targeted_for_essid(b"AndroidAP_9981")
    assert family == "imei"
    cand = next(iter(gen))
    assert len(cand) == 8 and cand.isdigit()


def test_no_match_returns_none():
    assert tg.targeted_for_essid(b"MyHomeWifi") == (None, None)


def test_budget_bounds_generator():
    _, gen = tg.targeted_for_essid(b"Tenda_ABC123", budget=10)
    assert len(list(gen)) == 10


def test_family_dedup_across_essids():
    cands = list(tg.targeted_candidates([b"NETGEAR11", b"NETGEAR22"], budget=5))
    assert len(cands) == 5  # one netgear pass, not two


def test_shared_keyspace_dedup_across_families():
    # netgear and spectrum share the word-word-digits keyspace; a work
    # unit holding both must stream it once, not twice
    cands = list(
        tg.targeted_candidates([b"NETGEAR11", b"MySpectrumWiFi88"], budget=7)
    )
    assert len(cands) == 7


def test_update_manifest_with_archive_md5(tmp_path):
    api = _FakeUpdateAPI("9.9.9 0123456789abcdef0123456789abcdef")
    c = _update_client(tmp_path, api)
    assert c.check_update() is True
    assert api.downloads[0][2] == "0123456789abcdef0123456789abcdef"


def test_update_rejects_html_manifest(tmp_path):
    api = _FakeUpdateAPI("<html>dwpa server</html>")
    assert _update_client(tmp_path, api).check_update() is False


# ---------------------------------------------------------------------------
# self-update


def test_version_tuple_ordering():
    assert version_tuple("2.3.1") > version_tuple("2.3")
    assert version_tuple("0.2.0") > version_tuple("0.1.9")
    assert version_tuple("1.0.0a") > version_tuple("1.0.0")
    assert version_tuple("0.1.0") == version_tuple("0.1.0")


class _FakeUpdateAPI:
    def __init__(self, remote, fail_download=False):
        self._remote = remote
        self.fail_download = fail_download
        self.downloads = []

    def remote_version(self):
        return self._remote

    def download(self, url, dest, expected_md5=None, max_tries=None):
        if self.fail_download:
            raise ConnectionError("nope")
        assert max_tries, "update downloads must bound their retries"
        self.downloads.append((url, dest, expected_md5))
        with open(dest, "wb") as f:
            f.write(b"new-archive")
        return dest


def _update_client(tmp_path, api):
    cfg = ClientConfig(base_url="http://x/", workdir=str(tmp_path / "w"))
    return TpuCrackClient(cfg, api=api, log=lambda *a: None)


def test_check_update_downloads_newer(tmp_path):
    api = _FakeUpdateAPI("9.9.9")
    c = _update_client(tmp_path, api)
    assert c.check_update() is True
    assert api.downloads[0][0] == "hc/dwpa_tpu.pyz"
    assert os.path.exists(api.downloads[0][1])


def test_check_update_skips_same_or_absent(tmp_path):
    assert _update_client(tmp_path, _FakeUpdateAPI("")).check_update() is False
    assert _update_client(tmp_path, _FakeUpdateAPI("0.0.1")).check_update() is False


def test_check_update_survives_download_failure(tmp_path):
    c = _update_client(tmp_path, _FakeUpdateAPI("9.9.9", fail_download=True))
    assert c.check_update() is False  # keep cracking on a flaky mirror


# ---------------------------------------------------------------------------
# loopback: targeted family cracks a net with no dictionary word


def test_targeted_pass_cracks_isp_default(server, tmp_path):
    # PLDTWIFI00007 is candidate #8 of the phome family keyspace — pass 1
    # must crack it even though the served dict has no useful words.
    psk = b"PLDTWIFI00007"
    _ingest(server, [tfx.make_pmkid_line(psk, b"PLDTHOMEDSL", seed="tp1")])
    _add_dict(server, [b"useless-word-1"])
    client = _client(server, tmp_path, batch_size=64)
    stages = []
    client.log = lambda msg: stages.append(msg)
    work = client.api.get_work(client.dictcount)
    res = client.process_work(work)
    assert [f.psk for f in res.founds] == [psk]
    assert res.accepted
    # per-stage timing surfaced (SURVEY.md §5.1); "stage" = the residual
    # on-thread staging — packing moved to the feed's producer threads
    assert any(m.startswith("stages: stage+h2d=") for m in stages)
