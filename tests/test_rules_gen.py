"""Host-side candidate pipeline tests: rule engine, masks, generators."""

import gzip
import hashlib
import io

import pytest

from dwpa_tpu.gen import (
    DictStream,
    imei_candidates,
    luhn_check_digit,
    mask_keyspace,
    mask_words,
    md5_file,
    psk_candidates,
)
from dwpa_tpu.rules import RuleError, apply_rules, parse_rule, parse_rules


def apply(rule_text, word):
    return parse_rule(rule_text).apply(word)


@pytest.mark.parametrize(
    "rule,word,expected",
    [
        (":", b"pass", b"pass"),
        ("l", b"PaSS", b"pass"),
        ("u", b"pass", b"PASS"),
        ("c", b"passWORD", b"Password"),
        ("C", b"PassWord", b"pASSWORD"),
        ("t", b"PaSs", b"pAsS"),
        ("T0", b"pass", b"Pass"),
        ("T3", b"pass", b"pasS"),
        ("r", b"abcd", b"dcba"),
        ("d", b"ab", b"abab"),
        ("p2", b"ab", b"ababab"),
        ("f", b"abc", b"abccba"),
        ("{", b"abcd", b"bcda"),
        ("}", b"abcd", b"dabc"),
        ("$1", b"pass", b"pass1"),
        ("$1 $2 $3", b"pass", b"pass123"),
        ("^x", b"pass", b"xpass"),
        ("[", b"pass", b"ass"),
        ("]", b"pass", b"pas"),
        ("D1", b"pass", b"pss"),
        ("x13", b"abcdef", b"bcd"),
        ("O12", b"abcdef", b"adef"),
        ("o2X", b"abcd", b"abXd"),
        ("'3", b"abcdef", b"abc"),
        ("sab", b"banana", b"bbnbnb"),
        ("@a", b"banana", b"bnn"),
        ("z2", b"ab", b"aaab"),
        ("Z2", b"ab", b"abbb"),
        ("q", b"ab", b"aabb"),
        ("k", b"abcd", b"bacd"),
        ("K", b"abcd", b"abdc"),
        ("*03", b"abcd", b"dbca"),
        ("+0", b"abc", b"bbc"),
        ("-0", b"bbc", b"abc"),
        (".0", b"abc", b"bbc"),
        (",1", b"abc", b"aac"),
        ("y2", b"abcd", b"ababcd"),
        ("Y2", b"abcd", b"abcdcd"),
        ("T9", b"pass", b"pass"),  # out-of-range position: no-op
        ("u $! T0", b"pass", b"pASS!"),
    ],
)
def test_rule_semantics(rule, word, expected):
    assert apply(rule, word) == expected


def test_insert_arity():
    # 'i' takes position + single char
    assert apply("i2X", b"abcd") == b"abXcd"


def test_reject_rules():
    assert apply("<5", b"pass") == b"pass"
    assert apply("<4", b"pass") is None
    assert apply(">3", b"pass") == b"pass"
    assert apply(">4", b"pass") is None
    assert apply("_4", b"pass") == b"pass"
    assert apply("_5", b"pass") is None
    assert apply("!x", b"pass") == b"pass"
    assert apply("!a", b"pass") is None
    assert apply("/a", b"pass") == b"pass"
    assert apply("/x", b"pass") is None
    assert apply("(p", b"pass") == b"pass"
    assert apply(")s", b"pass") == b"pass"
    assert apply("=0p", b"pass") == b"pass"
    assert apply("=0q", b"pass") is None
    assert apply("%2s", b"pass") == b"pass"
    assert apply("%3s", b"pass") is None


def test_parse_rules_skips_bad_lines():
    rules = parse_rules(["# comment", "", "l", "Mbogus", "u"])
    assert [r.text for r in rules] == ["l", "u"]
    with pytest.raises(RuleError):
        parse_rules(["Mbogus"], on_error="raise")


def test_apply_rules_expansion_order():
    rules = parse_rules([":", "u", "$1"])
    out = list(apply_rules(rules, [b"ab", b"cd"]))
    assert out == [b"ab", b"AB", b"ab1", b"cd", b"CD", b"cd1"]


def test_mask_generator():
    assert mask_keyspace("?d?d") == 100
    words = list(mask_words("?d?d"))
    assert words[0] == b"00" and words[-1] == b"99" and len(words) == 100
    assert list(mask_words("a?dc", limit=2)) == [b"a0c", b"a1c"]
    # keyspace slicing lines up with full enumeration
    assert list(mask_words("?d?d", skip=42, limit=3)) == [b"42", b"43", b"44"]
    assert mask_keyspace("?d?d?d?d?d?d?d?d") == 10**8


def test_luhn():
    # classic Luhn example: 7992739871 -> check digit 3
    assert luhn_check_digit("7992739871") == 3
    for cand in imei_candidates("35294906", serial_range=(0, 10)):
        assert len(cand) == 8 and cand.isdigit()
    cands = list(imei_candidates("3529490612345"))
    assert len(cands) == 10  # one free digit


def test_psk_candidates():
    mac = bytes.fromhex("a0b1c2d3e4f5")
    cands = list(psk_candidates(b"MyNet-4521", mac_ap=mac))
    assert all(8 <= len(c) <= 63 for c in cands)
    assert len(cands) == len(set(cands))
    assert b"00004521" in cands  # embedded digit run, zero-padded
    assert b"a0b1c2d3e4f5" in cands  # full BSSID hex


def test_dict_stream(tmp_path):
    words = b"alpha\nbeta\n\ngamma\n"
    plain = tmp_path / "d.txt"
    plain.write_bytes(words)
    gz = tmp_path / "d.txt.gz"
    gz.write_bytes(gzip.compress(words))
    for p in (plain, gz):
        assert list(DictStream(str(p))) == [b"alpha", b"beta", b"gamma"]
    assert list(DictStream(str(gz), skip=1, limit=1)) == [b"beta"]
    assert list(DictStream(str(plain)).batches(2)) == [[b"alpha", b"beta"], [b"gamma"]]
    assert md5_file(str(plain)) == hashlib.md5(words).hexdigest()


def test_dict_stream_fileobj():
    buf = io.BufferedReader(io.BytesIO(b"one1234\ntwo5678\n"))
    assert list(DictStream(buf)) == [b"one1234", b"two5678"]


def test_dictstream_reiterates_caller_fileobj():
    """A caller-supplied fileobj survives iteration and can be re-read
    (ADVICE r1: DictStream used to close it after the first pass)."""
    import io
    from dwpa_tpu.gen.dicts import DictStream

    buf = io.BytesIO(b"alpha\nbeta\n\ngamma\n")
    ds = DictStream(buf)
    assert list(ds) == [b"alpha", b"beta", b"gamma"]
    assert list(ds) == [b"alpha", b"beta", b"gamma"]
    assert not buf.closed


def test_dictstream_sniffs_gzip_bytesio():
    import gzip, io
    from dwpa_tpu.gen.dicts import DictStream

    buf = io.BytesIO(gzip.compress(b"one\ntwo\n"))
    assert list(DictStream(buf)) == [b"one", b"two"]
    assert list(DictStream(buf)) == [b"one", b"two"]


# ---------------------------------------------------------------------------
# the bundled WPA ruleset (the bestWPA.rule asset equivalent)


def test_wpa_rule_asset_fully_parses():
    from dwpa_tpu.rules import WPA_RULE_PATH, parse_rules, wpa_rules

    with open(WPA_RULE_PATH) as f:
        lines = [ln for ln in f.read().splitlines()
                 if ln.strip() and not ln.lstrip().startswith("#")]
    rules = parse_rules(lines, on_error="raise")  # every line must parse
    assert len(rules) == len(lines) == len(wpa_rules())
    assert len(rules) >= 100  # a real ruleset, not a stub


def test_wpa_rules_expand_expected_shapes():
    from dwpa_tpu.rules import apply_rules, wpa_rules

    out = set(apply_rules(wpa_rules(), [b"password"]))
    for expect in (b"password", b"Password", b"PASSWORD", b"password1",
                   b"password123", b"password2024", b"p@ssword",
                   b"passw0rd", b"drowssap", b"passwordpassword"):
        assert expect in out, expect


def test_apply_rules_pooled_matches_serial():
    """workers>1 must yield the exact serial stream (order included) —
    resume skip-by-count depends on it."""
    from dwpa_tpu.rules import apply_rules, parse_rules

    rules = parse_rules([":", "c", "$1", "se3", "r", "] ]"])
    words = [b"poolword%04d" % i for i in range(500)]
    serial = list(apply_rules(rules, words))
    # force_pool: the few-cores guard must not silently serialize the
    # very path this test exists to pin.
    pooled = list(apply_rules(rules, iter(words), workers=3, force_pool=True))
    assert pooled == serial


def test_apply_rules_pool_guard_falls_back_serial(monkeypatch, caplog):
    """On a host without spare cores the pool is auto-disabled (with a
    warning) and the serial stream is produced instead — --rule-workers
    must never make a deployment slower (BENCH_r03 host_feed)."""
    import logging

    from dwpa_tpu.rules import apply_rules, parse_rules
    from dwpa_tpu.rules import engine as eng

    rules = parse_rules([":", "u", "$9"])
    words = [b"guardword%02d" % i for i in range(20)]
    monkeypatch.setattr(eng, "_usable_cpus", lambda: 2)
    monkeypatch.setattr(eng, "_POOL_GUARD_WARNED", set())

    def boom(*a, **k):  # the pool must not even be touched
        raise AssertionError("pool used despite guard")

    monkeypatch.setattr(eng, "_apply_rules_pooled", boom)
    with caplog.at_level(logging.WARNING, logger="dwpa_tpu.rules.engine"):
        out = list(apply_rules(rules, words, workers=8))
    assert out == list(apply_rules(rules, words))
    assert any("pool disabled" in r.message for r in caplog.records)
