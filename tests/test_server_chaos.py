"""Server-side chaos: seeded sqlite fault injection at the Database
seam, crash-point consistency at every statement boundary, the 429/503
admission contract through the real client retry stack, and a threaded
load storm with mid-storm core restarts judged by the lease-ledger
invariant sweep.

Everything is seed-driven (DbFaultPlan / FaultPlan / VirtualClock): a
soak failure replays from its seed, never from a lucky interleaving.
"""

import json
import random
import sqlite3
import threading

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.chaos import (ChaosTransport, DbFaultPlan, FaultPlan,
                            SimulatedCrash, VirtualClock, WsgiTransport,
                            install_db_faults, sweep_invariants)
from dwpa_tpu.client.protocol import (CircuitBreaker, ServerAPI,
                                      classify_error, retry_after_floor)
from dwpa_tpu.server import Database, ServerCore, make_wsgi_app

PSKS = [b"storm-psk-%02d" % i for i in range(8)]


def _core(db=None, nets=4, dicts=2, **kw):
    core = ServerCore(db or Database(":memory:"), **kw)
    for i in range(nets):
        core.add_hashlines(
            [tfx.make_pmkid_line(PSKS[i % len(PSKS)], b"StormNet%d" % i,
                                 seed=f"st{i}")])
    core.db.x("UPDATE nets SET algo = ''")
    for i in range(dicts):
        core.add_dict(f"dict/st{i}.txt.gz", f"st{i}", "0" * 32, 10 + i)
    return core


def _api(app, clock=None, plan=None, **kw):
    clock = clock if clock is not None else VirtualClock()
    kw.setdefault("max_tries", 0)
    kw.setdefault("backoff", 0.01)
    kw.setdefault("rng", random.Random(7))
    kw.setdefault("sleep", clock.sleep)
    kw.setdefault("breaker", CircuitBreaker(threshold=50, cooldown=1.0,
                                            clock=clock.now))
    api = ServerAPI("http://loopback/", **kw)
    api.retry.clock = clock.now
    wsgi = WsgiTransport(app)
    api._transport = wsgi if plan is None else ChaosTransport(
        wsgi, plan, sleep=clock.sleep)
    return api, clock


# -- DbFaultPlan ------------------------------------------------------------


def test_db_fault_plan_same_seed_identical_schedule():
    verbs = ["select", "insert", "update", "begin", "commit"] * 40
    runs = []
    for _ in range(2):
        plan = DbFaultPlan(1234, rate=0.2)
        for v in verbs:
            plan.next_fault(v)
        runs.append(plan.schedule())
    assert runs[0] == runs[1]
    assert any(kind for _, _, kind in runs[0])  # rate actually fired
    # a different seed yields a different schedule
    other = DbFaultPlan(4321, rate=0.2)
    for v in verbs:
        other.next_fault(v)
    assert other.schedule() != runs[0]


def test_db_fault_plan_force_fifo_and_validation():
    plan = DbFaultPlan(0)
    plan.force("insert", "op_error").force("insert", "crash")
    assert plan.next_fault("select") is None
    assert plan.next_fault("insert") == "op_error"
    assert plan.next_fault("insert") == "crash"
    assert plan.next_fault("insert") is None
    plan.force_at(6, "disk_io")
    assert plan.next_fault("update") is None   # index 4
    assert plan.next_fault("update") is None   # index 5
    assert plan.next_fault("update") == "disk_io"  # index 6
    assert plan.kinds_injected() == {"op_error", "crash", "disk_io"}
    with pytest.raises(ValueError):
        plan.force("insert", "meteor")
    with pytest.raises(ValueError):
        plan.force_at(0, "meteor")


def test_install_injects_and_uninstalls():
    core = _core()
    plan = DbFaultPlan(0)
    uninstall = install_db_faults(core.db, plan)
    plan.force("select", "op_error")
    with pytest.raises(sqlite3.OperationalError, match="locked"):
        core.db.q("SELECT * FROM nets")
    plan.force("select", "disk_io")
    with pytest.raises(sqlite3.OperationalError, match="disk I/O"):
        core.db.q("SELECT * FROM nets")
    assert core.db.q1("SELECT COUNT(*) c FROM nets")["c"] == 4  # healthy
    uninstall()
    assert len(plan.schedule()) == 3  # post-uninstall statements unlogged
    core.db.q("SELECT * FROM nets")
    assert len(plan.schedule()) == 3


def test_mid_transaction_fault_rolls_back_whole_unit():
    """An OperationalError in the middle of the get_work lease loop must
    leave NO trace: no lease row, no partial n2d coverage."""
    core = _core(nets=2, dicts=2)
    plan = DbFaultPlan(0)
    uninstall = install_db_faults(core.db, plan)
    plan.force("insert", "disk_io")  # first INSERT = the leases row
    with pytest.raises(sqlite3.OperationalError):
        core.get_work(2)
    uninstall()
    assert core.db.q1("SELECT COUNT(*) c FROM leases")["c"] == 0
    assert core.db.q1("SELECT COUNT(*) c FROM n2d")["c"] == 0
    assert sweep_invariants(core.db) == []
    # and the core still works afterwards
    assert core.get_work(1) is not None


def test_crash_at_every_statement_boundary():
    """Kill the 'process' before statement 0, 1, 2, ... of get_work and
    put_work; after every crash the reopened ledger must pass the
    invariant sweep — no orphan coverage, no half-accepted net."""

    def run_ops(core):
        w = core.get_work(1)
        if w is not None:
            cand = [{"k": "%012x" % 0, "v": "00"}]  # rejected claim is fine
            core.put_work({"hkey": w["hkey"], "epoch": w["epoch"],
                           "cand": cand})

    # pass 1: count the statements the op sequence executes
    probe = _core(nets=2, dicts=2)
    counter = DbFaultPlan(0)
    uninstall = install_db_faults(probe.db, counter)
    run_ops(probe)
    uninstall()
    nstatements = len(counter.schedule())
    assert nstatements > 10  # the multi-statement paths are really there

    # pass 2: crash at each boundary, sweep after each
    for at in range(nstatements):
        core = _core(nets=2, dicts=2)
        plan = DbFaultPlan(0).force_at(at, "crash")
        uninstall = install_db_faults(core.db, plan)
        try:
            run_ops(core)
        except SimulatedCrash:
            pass
        uninstall()
        # "restart": a fresh handle over the same (in-memory) connection
        # state — the uncommitted transaction was rolled back at crash
        bad = sweep_invariants(core.db)
        assert bad == [], (at, bad)
        # the restarted core keeps functioning (lease or re-lease)
        core.get_work(1)
        assert sweep_invariants(core.db) == [], at


def test_sweep_invariants_detects_damage():
    core = _core(nets=2, dicts=1)
    w = core.get_work(1)
    assert sweep_invariants(core.db) == []
    # orphan coverage: in-flight row whose lease is gone
    core.db.x("DELETE FROM leases WHERE hkey = ?", (w["hkey"],))
    bad = sweep_invariants(core.db)
    assert any("orphan in-flight" in b for b in bad)
    # hollow lease: live lease with no coverage
    core.db.x("DELETE FROM n2d")
    core.db.x("INSERT INTO leases(hkey, epoch, issued) VALUES ('h0', 9, 1)")
    bad = sweep_invariants(core.db)
    assert any("hollow live lease" in b for b in bad)
    # coverage residue under a cracked net
    core.db.x("DELETE FROM leases")
    core.db.x("UPDATE nets SET n_state = 1")
    core.db.x("INSERT INTO n2d(net_id, d_id) SELECT net_id, 1 FROM nets LIMIT 1")
    bad = sweep_invariants(core.db)
    assert any("cracked net" in b for b in bad)


# -- 429/503 through the real retry stack -----------------------------------


def test_classify_429_and_retry_after_floor():
    import io
    import urllib.error

    def http(code, hdrs=None):
        return urllib.error.HTTPError("u", code, "m", hdrs, io.BytesIO(b""))

    assert classify_error(http(429)) == ("transient", "http_429")
    assert classify_error(http(503)) == ("transient", "http_5xx")
    assert classify_error(http(404)) == ("permanent", "http_4xx")
    assert retry_after_floor(http(429, {"Retry-After": "3"})) == 3.0
    assert retry_after_floor(http(429, {"Retry-After": "nope"})) == 0.0
    assert retry_after_floor(http(429)) == 0.0
    assert retry_after_floor(ConnectionResetError()) == 0.0


def test_http_429_transient_with_retry_after_floor_loopback():
    """An overloaded server's 429 must be retried (not fail-fast like
    other 4xx) and its Retry-After must floor the backoff: with a 10 ms
    backoff base, the virtual clock still advances by the server's
    2 s hint before the retry that succeeds."""
    core = _core(nets=2, dicts=1)
    core.max_inflight = 1
    app = make_wsgi_app(core)
    api, clock = _api(app)

    w1 = api.get_work(1)  # occupies the single admission slot

    # second get_work: first attempt 429s; release the slot so the
    # retry (after the floored backoff) succeeds.
    released = {}

    def sleeper(seconds):
        clock.sleep(seconds)
        if not released:
            released["done"] = True
            core.put_work({"hkey": w1["hkey"], "epoch": w1["epoch"],
                           "cand": []})

    api.sleep = sleeper
    t0 = clock.now()
    w2 = api.get_work(1)
    assert w2 is not None and w2["hkey"] != w1["hkey"]
    assert clock.now() - t0 >= 2.0  # Retry-After floored the 10 ms base
    assert sweep_invariants(core.db) == []


def test_http_503_on_db_contention_loopback():
    """A db-locked OperationalError surfaces as 503 + Retry-After; the
    client retries through it and the retry lands."""
    core = _core(nets=1, dicts=1)
    app = make_wsgi_app(core)
    api, clock = _api(app)
    plan = DbFaultPlan(0).force("begin", "op_error")
    uninstall = install_db_faults(core.db, plan)
    t0 = clock.now()
    w = api.get_work(1)
    uninstall()
    assert w is not None
    assert clock.now() - t0 >= 2.0  # the 503's Retry-After floored backoff
    assert "op_error" in plan.kinds_injected()


def test_chaos_http_429_kind_under_client_stack():
    """The transport-level injected 429 (chaos kind) is retried and its
    Retry-After honored — no server involved."""
    core = _core(nets=1, dicts=1)
    plan = FaultPlan(3)
    plan.force("get_work", "http_429")
    api, clock = _api(make_wsgi_app(core), plan=plan)
    t0 = clock.now()
    w = api.get_work(1)
    assert w is not None
    assert clock.now() - t0 >= 2.0
    assert plan.kinds_injected() == {"http_429"}


# -- seeded soak: load storm + db faults + mid-storm restarts ---------------


def _accepted_claims(core) -> float:
    return core.registry.value(
        "dwpa_server_claims_total", verdict="accepted") or 0.0


@pytest.mark.slow
def test_server_chaos_soak_storm(tmp_path, lock_witness):
    """Threaded client storm against a file-backed core with seeded db
    faults and two mid-storm core restarts.  Afterwards the reopened
    ledger passes the invariant sweep, every cracked net was accepted
    exactly once (no duplicate credits), and a single-threaded seeded
    leg replays an identical fault schedule run-to-run."""
    SEED = 20260805

    # -- deterministic replay leg: same seed => identical schedule
    def quiet_leg(sub):
        core = _core(Database(str(tmp_path / sub)), nets=3, dicts=2)
        plan = DbFaultPlan(SEED, rate=0.05)
        uninstall = install_db_faults(core.db, plan)
        ops = []
        for _ in range(12):
            try:
                w = core.get_work(1)
            except sqlite3.OperationalError:
                ops.append("oe")
                continue
            except SimulatedCrash:
                ops.append("crash")
                continue
            if w is None:
                ops.append("none")
                continue
            ops.append("work")
            try:
                core.put_work({"hkey": w["hkey"], "epoch": w["epoch"],
                               "cand": []})
            except (sqlite3.OperationalError, SimulatedCrash):
                ops.append("put-fault")
        uninstall()
        assert sweep_invariants(core.db) == []
        return ops, plan.schedule()

    ops_a, sched_a = quiet_leg("replay-a.sqlite")
    ops_b, sched_b = quiet_leg("replay-b.sqlite")
    assert sched_a == sched_b
    assert ops_a == ops_b

    # Every lock the storm creates (cores across restarts, retry
    # stacks, queues) reports to the witness: an acquisition-order
    # cycle fails the soak regardless of interleaving luck.
    with lock_witness(label="server chaos storm"):
        # -- the storm: threads x ops through the real WSGI app + retry stack
        dbpath = str(tmp_path / "storm.sqlite")
        seed_core = _core(Database(dbpath), nets=8, dicts=3)
        psk_by_essid = {("StormNet%d" % i).encode(): PSKS[i % len(PSKS)]
                        for i in range(8)}
        seed_core.db.conn.close()

        state = {"gen": 0}
        accepted_total = [0.0]
        holder = {}
        swap_lock = threading.Lock()

        def open_core():
            from dwpa_tpu.obs import MetricsRegistry

            # fresh registry per generation: banking the accept counter at
            # each restart must not re-count the shared process-wide one
            core = ServerCore(Database(dbpath), max_inflight=64,
                              registry=MetricsRegistry())
            holder["core"] = core
            holder["app"] = make_wsgi_app(core)
            return core

        open_core()

        def restart():
            """Mid-storm core 'kill': bank the old core's accept counter,
            drop its connection without any graceful shutdown, reopen."""
            with swap_lock:
                old = holder["core"]
                accepted_total[0] += _accepted_claims(old)
                state["gen"] += 1
                try:
                    old.db.conn.close()
                except sqlite3.Error:
                    pass
                open_core()

        def app_proxy(environ, start_response):
            with swap_lock:
                app = holder["app"]
            return app(environ, start_response)

        errs = []
        stop = threading.Event()

        def client_thread(idx):
            from dwpa_tpu.models import hashline as hl

            rng = random.Random(SEED + idx)
            api, clock = _api(app_proxy, max_tries=4, backoff=0.01,
                              rng=random.Random(SEED + idx))
            try:
                for _ in range(30):
                    if stop.is_set():
                        return
                    try:
                        w = api.get_work(1)
                    except ConnectionError:
                        continue
                    except RuntimeError:
                        continue  # "No nets"/version sentinels
                    cand = []
                    if rng.random() < 0.5:  # half the units get cracked
                        for line in w["hashes"]:
                            h = hl.parse(line)
                            psk = psk_by_essid.get(h.essid)
                            if psk:
                                cand.append({"k": h.mac_ap.hex(),
                                             "v": psk.hex()})
                    try:
                        api.put_work(w["hkey"], cand, epoch=w.get("epoch"))
                    except ConnectionError:
                        pass
            except Exception as e:  # pragma: no cover - storm must not leak
                errs.append(e)

        threads = [threading.Thread(target=client_thread, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        # two mid-storm restarts while clients are live
        import time as _time
        _time.sleep(0.3)
        restart()
        _time.sleep(0.3)
        restart()
        for t in threads:
            t.join(60)
        stop.set()
        assert not errs

        # bank the final generation and judge the ledger from a fresh handle
        accepted_total[0] += _accepted_claims(holder["core"])
        holder["core"].db.conn.close()
        final = Database(dbpath)
        assert sweep_invariants(final) == []
        cracked = final.q1(
            "SELECT COUNT(*) c FROM nets WHERE n_state = 1")["c"]
        # zero duplicate accepted founds: every accept event corresponds to
        # exactly one net crossing into n_state=1 (acceptance is idempotent
        # across duplicate submits and restarts)
        assert accepted_total[0] == cracked
        assert state["gen"] == 2
