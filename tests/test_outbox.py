"""Unit tests for the durable found outbox (dwpa_tpu/client/outbox.py).

The journal is the durability point between crack and server ack, so
every promise it makes — reopen fidelity, torn-tail tolerance, ack
idempotence, replay dedup, drain ordering — gets its own test, with the
corruption states produced by the chaos fs-fault injector rather than
hand-rolled byte surgery.
"""

import os

from dwpa_tpu.chaos import FsFaultInjector, flip_byte, tear_tail
from dwpa_tpu.client.outbox import (FILE_MAGIC, JOURNAL_NAME, FoundOutbox,
                                    _frame, _walk_frames)
from dwpa_tpu.obs import MetricsRegistry


def _cand(k, v):
    return {"k": k, "v": v}


def test_roundtrip_and_reopen(tmp_path):
    box = FoundOutbox(str(tmp_path))
    sent = box.record("hk1", [_cand("aa", "70736b31"), _cand("bb", "70736b32")])
    assert [c["k"] for c in sent] == ["aa", "bb"]
    assert box.pending_count() == 2
    box.close()

    # Reopen: pending founds survive verbatim, in journaled order.
    box2 = FoundOutbox(str(tmp_path))
    assert box2.pending() == {
        "hk1": [_cand("aa", "70736b31"), _cand("bb", "70736b32")]}
    box2.close()


def test_journal_created_lazily(tmp_path):
    box = FoundOutbox(str(tmp_path))
    assert not os.path.exists(box.path)  # nothing cracked, nothing written
    box.record("hk", [_cand("aa", "01")])
    assert open(box.path, "rb").read().startswith(FILE_MAGIC)
    box.close()


def test_ack_idempotent_and_persistent(tmp_path):
    box = FoundOutbox(str(tmp_path))
    cand = [_cand("aa", "01")]
    box.record("hk", cand)
    box.ack("hk", cand)
    size_after_first = os.path.getsize(box.path)
    box.ack("hk", cand)  # second ack must not grow the journal
    assert os.path.getsize(box.path) == size_after_first
    assert box.pending_count() == 0
    box.close()

    # After reopen the acked key is remembered: record() drops it so the
    # server never sees the same found twice.
    box2 = FoundOutbox(str(tmp_path))
    assert box2.record("hk", cand) == []
    assert box2.pending_count() == 0
    box2.close()


def test_replay_dedups_latest_value_wins(tmp_path):
    box = FoundOutbox(str(tmp_path))
    box.record("hk", [_cand("aa", "01")])
    box.record("hk", [_cand("aa", "02")])  # re-crack, new value
    box.close()
    box2 = FoundOutbox(str(tmp_path))
    assert box2.pending() == {"hk": [_cand("aa", "02")]}
    box2.close()


def test_torn_tail_skipped_not_fatal(tmp_path):
    box = FoundOutbox(str(tmp_path))
    box.record("hk", [_cand("aa", "01"), _cand("bb", "02")])
    box.ack("hk", [_cand("bb", "02")])
    box.close()

    # Power loss mid-append of the ack frame: the ack is gone, so "bb"
    # correctly reverts to pending (an un-durable ack never happened).
    tear_tail(box.path, 5)
    box2 = FoundOutbox(str(tmp_path))
    assert box2.pending_count() == 2
    assert box2.pending()["hk"][0] == _cand("aa", "01")
    box2.close()


def test_crc_flip_truncates_at_bad_frame(tmp_path):
    box = FoundOutbox(str(tmp_path))
    box.record("hk", [_cand("aa", "01")])
    box.record("hk", [_cand("bb", "02")])
    box.close()

    # Flip a byte inside the LAST frame: the first frame still replays,
    # the corrupt one is dropped — skip, not fatal.
    flip_byte(box.path, -3)
    box2 = FoundOutbox(str(tmp_path))
    assert box2.pending() == {"hk": [_cand("aa", "01")]}
    # The compacted journal is clean again: append + reopen both work.
    box2.record("hk", [_cand("cc", "03")])
    box2.close()
    box3 = FoundOutbox(str(tmp_path))
    assert box3.pending_count() == 2
    box3.close()


def test_seeded_fs_fault_sweep_never_fatal(tmp_path):
    """Any torn tail the injector produces must reopen cleanly — the
    journal's core promise, swept over seeded corruption states."""
    for seed in range(8):
        d = tmp_path / f"s{seed}"
        box = FoundOutbox(str(d))
        for i in range(4):
            box.record(f"hk{i}", [_cand(f"k{i}", f"{i:02x}")])
        box.close()
        inj = FsFaultInjector(seed)
        inj.tear(box.path, max_bytes=48)
        box2 = FoundOutbox(str(d))  # must not raise
        assert box2.pending_count() <= 4
        box2.close()
        assert inj.log and inj.log[0][0] == "tear"


def test_unrecognizable_journal_preserved(tmp_path):
    p = tmp_path / JOURNAL_NAME
    p.write_bytes(b"this is not an outbox journal")
    box = FoundOutbox(str(tmp_path))
    assert box.pending_count() == 0
    assert (tmp_path / (JOURNAL_NAME + ".corrupt")).read_bytes().startswith(
        b"this is")
    box.close()


def test_drain_ordering_and_partial_failure(tmp_path):
    box = FoundOutbox(str(tmp_path))
    box.record("hk1", [_cand("aa", "01")])
    box.record("hk2", [_cand("bb", "02")])
    box.record("hk3", [_cand("cc", "03")])

    calls = []

    def put_work(hkey, cand):
        calls.append(hkey)
        if hkey == "hk2":
            return False  # server rejected: stays pending, drain continues
        return True

    delivered = box.drain(put_work)
    assert calls == ["hk1", "hk2", "hk3"]  # journaled order
    assert delivered == 2
    assert box.pending() == {"hk2": [_cand("bb", "02")]}

    # Transport failure stops the whole drain (server is down).
    def put_down(hkey, cand):
        calls.append("down")
        raise ConnectionError("refused")

    assert box.drain(put_down) == 0
    assert calls[-1] == "down" and calls.count("down") == 1
    assert box.pending_count() == 1
    box.close()


def test_compaction_bounds_journal(tmp_path):
    box = FoundOutbox(str(tmp_path))
    for i in range(20):
        box.record("hk", [_cand("aa", f"{i:02x}")])  # 20 frames, 1 live key
    box.close()
    grown = os.path.getsize(box.path)
    box2 = FoundOutbox(str(tmp_path))  # frames >> live: compacts on open
    assert os.path.getsize(box2.path) < grown
    assert box2.pending() == {"hk": [_cand("aa", "13")]}  # latest value
    box2.close()


def test_metrics_counters(tmp_path):
    reg = MetricsRegistry()
    box = FoundOutbox(str(tmp_path), registry=reg)
    box.record("hk", [_cand("aa", "01"), _cand("bb", "02")])
    box.ack("hk", [_cand("aa", "01")])
    assert reg.value("dwpa_outbox_pending_total") == 2
    assert reg.value("dwpa_outbox_acked_total") == 1
    box.close()


def test_frame_walker_rejects_bad_magic(tmp_path):
    blob = FILE_MAGIC + _frame({"op": "found", "hkey": "h", "k": "a",
                                "v": "01"}) + b"XXXX" + _frame(
        {"op": "found", "hkey": "h", "k": "b", "v": "02"})
    recs = [r for r, _ in _walk_frames(blob)]
    assert [r["k"] for r in recs] == ["a"]  # stops at the bad magic


def test_concurrent_record_and_ack_keep_journal_intact(tmp_path):
    """Regression (concurrency rule DW302): record/ack hammered from
    threads must never tear a journal frame, double-create the file, or
    drop state — the mutators serialize on the outbox mutex.  Replay
    from a fresh handle is the ground truth."""
    import threading

    box = FoundOutbox(str(tmp_path))
    N = 60
    errs = []

    def recorder(tid):
        try:
            for i in range(N):
                box.record(f"hk{tid}", [_cand("%02x" % i, "%04x" % (tid + i))])
        except Exception as e:  # pragma: no cover - must not happen
            errs.append(e)

    def acker():
        try:
            for i in range(0, N, 2):
                box.ack("hk0", [_cand("%02x" % i, "ignored")])
        except Exception as e:  # pragma: no cover - must not happen
            errs.append(e)

    threads = [threading.Thread(target=recorder, args=(t,))
               for t in range(3)] + [threading.Thread(target=acker)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert errs == []
    box.close()

    # Every frame intact (no torn/interleaved writes), and replay agrees
    # with the in-memory verdict: acked keys gone, the rest pending.
    blob = open(box.path, "rb").read()
    frames = list(_walk_frames(blob))
    assert frames and frames[-1][1] == len(blob)  # walker consumed it all
    box2 = FoundOutbox(str(tmp_path))
    pend = box2.pending()
    assert len(pend.get("hk0", [])) == N - len(range(0, N, 2))
    assert len(pend["hk1"]) == N and len(pend["hk2"]) == N
    for i in range(0, N, 2):
        assert all(c["k"] != "%02x" % i for c in pend.get("hk0", []))
    box2.close()
