"""Subprocess worker for the multi-host mesh test (not collected by
pytest).  Forces the virtual CPU platform (the container pre-imports
jax, so env vars alone don't take — jax.config must be updated), joins
the two-process jax.distributed cluster, and runs the sharded crack
step over the global 8-device mesh with this host's candidate shard."""

import os
import sys


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from dwpa_tpu import testing as tfx
    from dwpa_tpu.models import hashline as hl
    from dwpa_tpu.models import m22000 as m
    from dwpa_tpu.parallel import build_crack_step
    from dwpa_tpu.parallel.mesh import multihost_mesh, shard_candidates
    from dwpa_tpu.utils import bytesops as bo

    mesh = multihost_mesh(coordinator=f"localhost:{port}",
                          num_processes=2, process_id=pid)
    # device count per process follows the caller's XLA_FLAGS (4 when
    # run standalone, 8 under the pytest env) — the mesh must span both
    # processes' devices either way
    assert mesh.size == 2 * jax.local_device_count(), mesh
    psk, essid = b"multihost99", b"MhNet"
    nets = [m.prep_net(hl.parse(tfx.make_pmkid_line(psk, essid, seed="mh")))]
    s1, s2 = m.essid_salt_blocks(essid)
    step = build_crack_step(mesh, nets, s1, s2)
    # Global batch of 16; the planted PSK lives in process 1's half, so
    # a hit on every process proves the cross-host psum.
    batch = 2 * mesh.size
    words = [b"mh-word%04d" % i for i in range(batch)]
    words[batch // 2 + 3] = psk  # in process 1's half
    local = words[pid * (batch // 2):(pid + 1) * (batch // 2)]
    pw = shard_candidates(mesh, bo.pack_passwords_be(local))
    hits, found, _ = step(pw)
    print(f"RESULT {pid} hits={int(np.asarray(hits))}", flush=True)

    # Full-engine find decode across hosts (ADVICE r2 medium): the
    # planted PSK again lives in process 1's shard, so process 0 can
    # only produce the Found via the replicated-gather + candidate
    # exchange in M22000Engine._gather_find_data — and both hosts must
    # decode the identical find to keep their engines in lockstep.
    eng = m.M22000Engine(
        [tfx.make_pmkid_line(psk, essid, seed="mh-eng")],
        mesh=mesh, batch_size=mesh.size,
    )
    batch2 = 2 * mesh.size
    words2 = [b"ng-word%04d" % i for i in range(batch2)]
    words2[batch2 // 2 + 1] = psk  # process 1's half
    local2 = words2[pid * (batch2 // 2):(pid + 1) * (batch2 // 2)]
    finds = eng.crack_batch(local2)
    got = finds[0].psk.decode() if finds else "NONE"
    pruned = len(eng.nets) == 0
    print(f"ENGINE {pid} finds={len(finds)} psk={got} pruned={pruned}",
          flush=True)

    # Mask-path find decode: candidates are generated on device from the
    # global keyspace index (_LazyWords), so there is no candidate
    # exchange — each host must materialize the hit word from the GLOBAL
    # column (a local-index lookup would fetch the wrong word whenever
    # the hit lives on a non-zero process's shard).  "123456?d?d" with
    # limit 8 puts PSK 12345607 at global column 7 — process 1's shard.
    eng2 = m.M22000Engine(
        [tfx.make_pmkid_line(b"12345607", b"MaskNet", seed="mh-mask")],
        mesh=mesh, batch_size=mesh.size,
    )
    finds2 = eng2.crack_mask("123456?d?d", skip=0, limit=8)
    got2 = finds2[0].psk.decode() if finds2 else "NONE"
    print(f"MASK {pid} finds={len(finds2)} psk={got2}", flush=True)

    # Partial final batch: limit=6 pads the generated batch to 8 mesh
    # columns, so keyspace words 6-7 exist on device but lie OUTSIDE the
    # requested window — word 5 must be found, word 7 must NOT (adjacent
    # distributed work units would otherwise double-claim it).  Pins the
    # global (not per-process) tail masking of the mask path's decode.
    eng3 = m.M22000Engine(
        [tfx.make_pmkid_line(b"12345605", b"MaskNet3", seed="mh-p1"),
         tfx.make_pmkid_line(b"12345607", b"MaskNet4", seed="mh-p2")],
        mesh=mesh, batch_size=mesh.size,
    )
    finds3 = eng3.crack_mask("123456?d?d", skip=0, limit=6)
    got3 = ",".join(sorted(f.psk.decode() for f in finds3))
    print(f"MASKPART {pid} finds={got3}", flush=True)

    # All-invalid local shard on process 0: _prepare must dispatch an
    # all-padding block (a skip would desync the shard_map collectives
    # and hang process 1 forever) and process 1's find still decodes on
    # both hosts through the candidate exchange.
    eng4 = m.M22000Engine(
        [tfx.make_pmkid_line(b"padlock-psk7", b"PadNet", seed="mh-pad")],
        mesh=mesh, batch_size=mesh.size,
    )
    if pid == 0:
        local4 = [b"x" * 70] * (batch2 // 2)  # every word too long
    else:
        local4 = [b"pw-%05d" % i for i in range(batch2 // 2)]
        local4[1] = b"padlock-psk7"
    finds4 = eng4.crack_batch(local4)
    got4 = finds4[0].psk.decode() if finds4 else "NONE"
    print(f"PAD {pid} finds={len(finds4)} psk={got4}", flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
