"""Subprocess worker for the multi-host mesh test (not collected by
pytest).  Forces the virtual CPU platform (the container pre-imports
jax, so env vars alone don't take — jax.config must be updated), joins
the two-process jax.distributed cluster, and runs the sharded crack
step over the global 8-device mesh with this host's candidate shard."""

import os
import sys


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    # Share the suite's persistent XLA cache: the shard_map step HLO is
    # identical run-to-run and dominates this worker's wall clock.
    from dwpa_tpu.utils.compcache import enable_compilation_cache

    enable_compilation_cache(os.path.join(
        os.path.dirname(__file__), "..", ".pytest_xla_cache"))

    from dwpa_tpu import testing as tfx
    from dwpa_tpu.models import hashline as hl
    from dwpa_tpu.models import m22000 as m
    from dwpa_tpu.parallel import build_crack_step
    from dwpa_tpu.parallel.mesh import multihost_mesh, shard_candidates
    from dwpa_tpu.utils import bytesops as bo

    mesh = multihost_mesh(coordinator=f"localhost:{port}",
                          num_processes=2, process_id=pid)
    # device count per process follows the caller's XLA_FLAGS (4 when
    # run standalone, 8 under the pytest env) — the mesh must span both
    # processes' devices either way
    assert mesh.size == 2 * jax.local_device_count(), mesh
    psk, essid = b"multihost99", b"MhNet"
    nets = [m.prep_net(hl.parse(tfx.make_pmkid_line(psk, essid, seed="mh")))]
    s1, s2 = m.essid_salt_blocks(essid)
    step = build_crack_step(mesh, nets, s1, s2)
    # Global batch of 16; the planted PSK lives in process 1's half, so
    # a hit on every process proves the cross-host psum.
    batch = 2 * mesh.size
    words = [b"mh-word%04d" % i for i in range(batch)]
    words[batch // 2 + 3] = psk  # in process 1's half
    local = words[pid * (batch // 2):(pid + 1) * (batch // 2)]
    pw = shard_candidates(mesh, bo.pack_passwords_be(local))
    hits, found, _ = step(pw)
    print(f"RESULT {pid} hits={int(np.asarray(hits))}", flush=True)

    # Full-engine find decode across hosts (ADVICE r2 medium): the
    # planted PSK again lives in process 1's shard, so process 0 can
    # only produce the Found via the replicated-gather + candidate
    # exchange in M22000Engine._gather_find_data — and both hosts must
    # decode the identical find to keep their engines in lockstep.
    eng = m.M22000Engine(
        [tfx.make_pmkid_line(psk, essid, seed="mh-eng")],
        mesh=mesh, batch_size=mesh.size,
    )
    batch2 = 2 * mesh.size
    words2 = [b"ng-word%04d" % i for i in range(batch2)]
    words2[batch2 // 2 + 1] = psk  # process 1's half
    local2 = words2[pid * (batch2 // 2):(pid + 1) * (batch2 // 2)]
    finds = eng.crack_batch(local2)
    got = finds[0].psk.decode() if finds else "NONE"
    pruned = len(eng.nets) == 0
    print(f"ENGINE {pid} finds={len(finds)} psk={got} pruned={pruned}",
          flush=True)

    # Mask-path find decode: candidates are generated on device from the
    # global keyspace index (_LazyWords), so there is no candidate
    # exchange — each host must materialize the hit word from the GLOBAL
    # column (a local-index lookup would fetch the wrong word whenever
    # the hit lives on a non-zero process's shard).  "123456?d?d" with
    # limit 8 puts PSK 12345607 at global column 7 — process 1's shard.
    eng2 = m.M22000Engine(
        [tfx.make_pmkid_line(b"12345607", b"MaskNet", seed="mh-mask")],
        mesh=mesh, batch_size=mesh.size,
    )
    finds2 = eng2.crack_mask("123456?d?d", skip=0, limit=8)
    got2 = finds2[0].psk.decode() if finds2 else "NONE"
    print(f"MASK {pid} finds={len(finds2)} psk={got2}", flush=True)

    # Partial final batch: limit=6 pads the generated batch to 8 mesh
    # columns, so keyspace words 6-7 exist on device but lie OUTSIDE the
    # requested window — word 5 must be found, word 7 must NOT (adjacent
    # distributed work units would otherwise double-claim it).  Pins the
    # global (not per-process) tail masking of the mask path's decode.
    eng3 = m.M22000Engine(
        [tfx.make_pmkid_line(b"12345605", b"MaskNet3", seed="mh-p1"),
         tfx.make_pmkid_line(b"12345607", b"MaskNet4", seed="mh-p2")],
        mesh=mesh, batch_size=mesh.size,
    )
    finds3 = eng3.crack_mask("123456?d?d", skip=0, limit=6)
    got3 = ",".join(sorted(f.psk.decode() for f in finds3))
    print(f"MASKPART {pid} finds={got3}", flush=True)

    # All-invalid local shard on process 0: _prepare must dispatch an
    # all-padding block (a skip would desync the shard_map collectives
    # and hang process 1 forever) and process 1's find still decodes on
    # both hosts through the candidate exchange.
    eng4 = m.M22000Engine(
        [tfx.make_pmkid_line(b"padlock-psk7", b"PadNet", seed="mh-pad")],
        mesh=mesh, batch_size=mesh.size,
    )
    if pid == 0:
        local4 = [b"x" * 70] * (batch2 // 2)  # every word too long
    else:
        local4 = [b"pw-%05d" % i for i in range(batch2 // 2)]
        local4[1] = b"padlock-psk7"
    finds4 = eng4.crack_batch(local4)
    got4 = finds4[0].psk.decode() if finds4 else "NONE"
    print(f"PAD {pid} finds={len(finds4)} psk={got4}", flush=True)

    # Device-rules path across processes (crack_rules' multi-process
    # contract): every host feeds the SAME global base stream; each
    # uploads only its row slice and decodes finds from the replicated
    # bit-packed mask — one PSK reachable only via a device rule ('u')
    # planted in process 1's row block, and one reachable only via a
    # host-expanded rule ('@b') planted in process 0's tail block (so
    # its find must cross hosts through the candidate exchange).
    from dwpa_tpu.rules import parse_rule, parse_rules

    gsize = 2 * mesh.size  # one global flush: batch_size rows per host
    base5 = [b"rulebase%02dx" % i for i in range(gsize)]
    psk_dev = parse_rule("u").apply(base5[mesh.size + 3])   # process 1 rows
    psk_tail = parse_rule("@b").apply(base5[2])             # process 0 block
    eng5 = m.M22000Engine(
        [tfx.make_pmkid_line(psk_dev, b"RuleNetDev", seed="mh-rdev"),
         tfx.make_pmkid_line(psk_tail, b"RuleNetTail", seed="mh-rtail")],
        mesh=mesh, batch_size=mesh.size,
    )
    finds5 = eng5.crack_rules(base5, parse_rules([":", "u", "@b"]))
    got5 = ",".join(sorted(f.psk.decode() for f in finds5))
    print(f"RULES {pid} finds={got5}", flush=True)

    # Mixed-kind ESSID group over the mesh: every verify kind — PMKID,
    # EAPOL keyver 1 (MD5 MIC), keyver 2 (SHA1 MIC), keyver 3 (AES-CMAC)
    # — assembled through _assemble_step, with the PSK in process 1's
    # shard so every kind's find rides the cross-host decode.
    psk6, essid6 = b"mixedkinds6", b"MixNet"
    lines6 = [
        tfx.make_eapol_line(psk6, essid6, keyver=2, seed="mh-k2"),
        tfx.make_pmkid_line(psk6, essid6, seed="mh-pmk"),
        tfx.make_eapol_line(psk6, essid6, keyver=1, seed="mh-k1"),
        tfx.make_eapol_line(psk6, essid6, keyver=3, seed="mh-k3"),
    ]
    eng6 = m.M22000Engine(lines6, mesh=mesh, batch_size=mesh.size)
    words6 = [b"mx-word%04d" % i for i in range(batch2)]
    words6[batch2 // 2 + 2] = psk6  # process 1's half
    local6 = words6[pid * (batch2 // 2):(pid + 1) * (batch2 // 2)]
    finds6 = eng6.crack_batch(local6)
    kinds6 = ",".join(str(k) for k in sorted(f.line.keyver for f in finds6))
    print(f"MIXED {pid} finds={len(finds6)} keyvers={kinds6}", flush=True)

    # Dense-find batch: more owned hit columns than MAX_FINDS_PER_BATCH
    # forces MULTIPLE fixed-shape allgather exchange rounds (the cap is
    # shrunk instance-side so the path triggers at test scale).  Expect
    # 1 nvalids-allgather + ceil(6/4)=2 exchange rounds = 3 calls.
    from jax.experimental import multihost_utils as mhu

    eng7 = m.M22000Engine(
        [tfx.make_pmkid_line(b"densepsk77", b"DenseNet", seed="mh-dense")],
        mesh=mesh, batch_size=mesh.size,
    )
    eng7.MAX_FINDS_PER_BATCH = 4
    words7 = [b"dn-word%04d" % i for i in range(batch2)]
    for k in range(6):  # six hit columns, all inside process 1's half
        words7[batch2 // 2 + 2 + k] = b"densepsk77"
    local7 = words7[pid * (batch2 // 2):(pid + 1) * (batch2 // 2)]
    calls = {"ex": 0}
    orig_ag = mhu.process_allgather

    def counting_ag(x, *a, **k):
        # exchange rounds are the fixed-shape uint8 [cap, 6+63] payloads
        # (jax internals also route through process_allgather, so count
        # only the candidate-exchange shape)
        if getattr(x, "ndim", None) == 2 and x.shape[0] == 4:
            calls["ex"] += 1
        return orig_ag(x, *a, **k)

    mhu.process_allgather = counting_ag
    finds7 = eng7.crack_batch(local7)
    mhu.process_allgather = orig_ag
    got7 = finds7[0].psk.decode() if finds7 else "NONE"
    print(f"DENSE {pid} finds={len(finds7)} psk={got7} "
          f"rounds={calls['ex']}", flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
