"""Device-stream executor (dwpa_tpu.parallel.streams).

Layers under test:

- PARITY — ``crack_streams`` vs ``crack_blocks`` over the identical
  framed feed (mixed keyvers + mixed ESSIDs): same found list, same
  per-block ``on_batch`` sequence (the resume-framing contract), and a
  warm second run under the recompile sentinel at ``allowed=0``;
- RESUME — a stream run resumed at ``skip=k`` covers exactly the
  lockstep path's unskipped tail;
- TELEMETRY — per-device ``dwpa_stream_*`` series and the
  ``stream:dispatch``/``stream:collect`` spans;
- FAULTS — a crashing stream's unfinished blocks requeue onto a
  survivor (excluded-style retry) without breaking demux order or
  leaking threads; a block out of eligible streams surfaces as
  ``StreamError`` with its global offset.

Real-engine tests run 3 streams (each stream compiles its own
single-device step per hash kind, so the stream count bounds the
compile bill) and share ``BATCH = 32`` with tests/test_sched.py so the
lockstep compiles are reused within a tier-1 run.  Fault tests use
fake engines — no device work at all.
"""

import threading
import types

import jax
import pytest

from dwpa_tpu import testing as synth
from dwpa_tpu.feed import frame_blocks
from dwpa_tpu.models.m22000 import M22000Engine
from dwpa_tpu.obs import MetricsRegistry
from dwpa_tpu.obs.spans import SpanTracer
from dwpa_tpu.parallel import StreamError, StreamExecutor
from dwpa_tpu.parallel.streams import (default_feed_workers, device_label,
                                       streams_default)

BATCH = 32
NSTREAMS = 3


def _lines():
    """Mixed keyvers + mixed ESSIDs; NetD is never cracked so neither
    path early-stops and consumed counts stay comparable."""
    return [
        synth.make_pmkid_line(b"stream-pass-a", b"StreamNetA", seed="st1"),
        synth.make_eapol_line(b"stream-pass-b", b"StreamNetB", keyver=2,
                              seed="st2"),
        synth.make_eapol_line(b"stream-pass-c", b"StreamNetC", keyver=3,
                              seed="st3"),
        synth.make_pmkid_line(b"not-in-keyspace", b"StreamNetD", seed="st4"),
    ]


def _words():
    """5 blocks of 32; the three PSKs land in different blocks."""
    words = [b"stjunk%04d" % i for i in range(160)]
    words[3] = b"stream-pass-a"
    words[40] = b"stream-pass-b"
    words[100] = b"stream-pass-c"
    return words


def _keys(founds):
    return sorted((f.line.essid, f.psk, f.nc, f.endian, f.pmk)
                  for f in founds)


def _batch_log(founds):
    return sorted(f.psk for f in founds)


def _stream_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(("stream-", "sched-stream-"))]


# ---------------------------------------------------------------------------
# parity with the lockstep path
# ---------------------------------------------------------------------------


def test_streams_match_lockstep_and_stay_compiled(recompile_sentinel):
    """The tentpole contract: identical found lists AND an identical
    per-block on_batch sequence (ordered demux = unchanged resume
    framing), then a warm rerun with zero recompiles."""
    lines, words = _lines(), _words()
    devices = jax.devices()[:NSTREAMS]

    lock_eng = M22000Engine(lines, batch_size=BATCH)
    lock_log = []
    lock_founds = lock_eng.crack_blocks(
        frame_blocks(iter(words), lock_eng.batch_size),
        on_batch=lambda c, f: lock_log.append((c, _batch_log(f))))

    reg = MetricsRegistry()
    tracer = SpanTracer(reg)
    st_eng = M22000Engine(lines, batch_size=BATCH)
    st_log = []
    st_founds = st_eng.crack_streams(
        frame_blocks(iter(words), st_eng.batch_size),
        on_batch=lambda c, f: st_log.append((c, _batch_log(f))),
        devices=devices, registry=reg, tracer=tracer)

    assert _keys(st_founds) == _keys(lock_founds)
    assert [p for _, ps in st_log for p in ps]  # founds reported per block
    assert st_log == lock_log
    assert sum(c for c, _ in st_log) == len(words)
    # both engines pruned their live view identically
    assert {n.line.essid for n in st_eng.nets} == \
        {n.line.essid for n in lock_eng.nets} == {b"StreamNetD"}
    assert _stream_threads() == []

    # telemetry: every series labeled by device, spans from stream side
    labels = [device_label(d) for d in devices]
    total = sum(reg.value("dwpa_stream_blocks_total", device=lb) or 0
                for lb in labels)
    assert total == len(st_log)
    for lb in labels:
        busy = reg.value("dwpa_stream_busy_fraction", device=lb)
        if busy is not None:         # a stream that got no block sets none
            assert 0.0 <= busy <= 1.0
        depth = reg.value("dwpa_stream_queue_depth", device=lb)
        assert depth is None or depth >= 0
    names = {r["name"] for r in tracer.records()}
    assert {"stream:dispatch", "stream:collect"} <= names

    # warm rerun: every per-device step is already in _STEP_CACHE
    warm = M22000Engine(lines, batch_size=BATCH)
    with recompile_sentinel(allowed=0, label="warm stream rerun"):
        warm_founds = warm.crack_streams(
            frame_blocks(iter(words), warm.batch_size), devices=devices)
    assert _keys(warm_founds) == _keys(lock_founds)


def test_streams_resume_skip_equivalence():
    """A stream run resumed at skip=k equals the lockstep run over the
    same unskipped tail: same found list, same consumed floor, and the
    first block keeps the global offset ``skip``."""
    lines, words = _lines(), _words()
    skip = 64   # past pass-a AND pass-b; only pass-c remains
    tail = words[skip:]
    devices = jax.devices()[:NSTREAMS]

    lock_eng = M22000Engine(lines, batch_size=BATCH)
    lock_founds = lock_eng.crack_blocks(
        frame_blocks(iter(tail), lock_eng.batch_size, base_offset=skip))

    st_eng = M22000Engine(lines, batch_size=BATCH)
    st_log = []
    blocks = list(frame_blocks(iter(tail), st_eng.batch_size,
                               base_offset=skip))
    offsets = [b.offset for b in blocks]
    st_founds = st_eng.crack_streams(
        iter(blocks), on_batch=lambda c, f: st_log.append(c),
        devices=devices)

    assert offsets[0] == skip
    assert _keys(st_founds) == _keys(lock_founds)
    assert {f.psk for f in st_founds} == {b"stream-pass-c"}
    assert sum(st_log) == len(tail)


def test_streams_default_policy():
    """Single-process multi-device (the forced-8-CPU test mesh) turns
    streams on; the feed defaults to one producer per device."""
    assert jax.process_count() == 1 and jax.local_device_count() == 8
    assert streams_default() is True
    assert default_feed_workers() == 8


# ---------------------------------------------------------------------------
# fault injection (fake engines — no device work)
# ---------------------------------------------------------------------------


class _FakeNet:
    def __init__(self, line):
        self.line = line


class _FakeEngine:
    """The slice of the engine surface DeviceStream touches."""

    PIPELINE_DEPTH = 3

    def __init__(self, lines, fail_offsets=()):
        self.nets = [_FakeNet(ln) for ln in lines]
        self.groups = {b"X": list(self.nets)}
        self.fail_offsets = set(fail_offsets)
        self.seen = []

    def _prepare_block(self, block):
        return block

    def _dispatch(self, prep):
        if prep.offset in self.fail_offsets:
            raise RuntimeError(f"injected fault at {prep.offset}")
        return prep

    def _collect(self, disp):
        self.seen.append(disp.offset)
        return []

    def remove(self, found):
        self.nets = [n for n in self.nets if n.line is not found.line]


def _fake_blocks(k, batch=32):
    return [types.SimpleNamespace(offset=i * batch, count=batch)
            for i in range(k)]


def _fake_devices(k):
    return [types.SimpleNamespace(platform="fake", id=i) for i in range(k)]


def test_stream_crash_requeues_to_survivor():
    """Stream 0 dies mid-run: its unfinished blocks go back to the
    queue with stream 0 excluded, the survivor completes them, demux
    order and counts are unchanged, and no stream thread leaks."""
    lines = [object(), object()]
    engines = {}

    def factory(device):
        fail = (64,) if device.id == 0 else ()
        engines[device.id] = _FakeEngine(lines, fail_offsets=fail)
        return engines[device.id]

    ex = StreamExecutor(factory, _fake_devices(2))
    blocks = _fake_blocks(6)
    log = []
    founds = ex.run(iter(blocks), on_batch=lambda c, f: log.append(c))
    assert founds == []
    assert log == [32] * 6                      # every block, in order
    assert len(ex.block_streams) == 6
    # the poisoned block (offset 64, seq 2) was completed by stream 1
    assert ex.block_streams[2] == 1
    assert 64 in engines[1].seen and 64 not in engines[0].seen
    assert _stream_threads() == []


def test_stream_crash_out_of_streams_is_fatal():
    """With a single stream there is no survivor to requeue onto: the
    run surfaces a StreamError carrying a failed block's global offset
    and still joins every thread.  The poison sits on the FIRST block
    so the unretryable block is deterministic."""
    def factory(device):
        return _FakeEngine([object()], fail_offsets=(0,))

    ex = StreamExecutor(factory, _fake_devices(1))
    with pytest.raises(StreamError) as err:
        ex.run(iter(_fake_blocks(6)))
    assert err.value.offset == 0
    assert "injected fault" in str(err.value)
    assert _stream_threads() == []


def test_stream_crash_everywhere_exhausts_attempts():
    """A block that fails on EVERY stream runs out of eligible streams
    and aborts instead of cycling the queue forever."""
    def factory(device):
        return _FakeEngine([object()], fail_offsets=(0,))

    ex = StreamExecutor(factory, _fake_devices(2), max_attempts=5)
    with pytest.raises(StreamError) as err:
        ex.run(iter(_fake_blocks(1)))
    assert err.value.offset == 0
    assert _stream_threads() == []


def test_stream_feed_error_propagates():
    """A feeder exception (FeedError &co) aborts the run with the
    ORIGINAL exception type — the client's retry layer keys off it."""
    class _Boom(Exception):
        pass

    def feed():
        yield from _fake_blocks(2)
        raise _Boom("source died")

    def factory(device):
        return _FakeEngine([object()])

    ex = StreamExecutor(factory, _fake_devices(2))
    with pytest.raises(_Boom):
        ex.run(feed())
    assert _stream_threads() == []


def test_stream_found_dedup_and_cross_stream_prune():
    """Every block claims the same net: the demux reports it once
    (first block in global order wins) and the prune lands on the
    stream's engine at a later block boundary.  A single stream plus a
    slow prepare on later blocks makes the emitter-vs-worker
    interleaving deterministic enough to observe the prune."""
    import time

    line = object()

    class _Hit:
        def __init__(self):
            self.line = line

    class _HitEngine(_FakeEngine):
        def _collect(self, disp):
            super()._collect(disp)
            if disp.offset > 0:
                time.sleep(0.05)  # let the emitter push block 0's prune
            return [_Hit()]       # every block claims the same net

    engines = {}

    def factory(device):
        engines[device.id] = _HitEngine([line])
        return engines[device.id]

    ex = StreamExecutor(factory, _fake_devices(1))
    founds = ex.run(iter(_fake_blocks(4)))
    assert len(founds) == 1       # deduped by line identity
    assert engines[0].nets == []  # the prune reached the live view
    assert _stream_threads() == []
