"""Mesh-aggregate rules pipeline (on-device expansion as pass 2).

Layers under test:

- DIFFERENTIAL PARITY — the three rules entry points
  (``crack_rules`` flat, ``crack_rules_blocks`` framed,
  ``crack_rules_streams`` per-device) against each other AND against
  a pure host-expansion reference (``Rule.apply`` + plain ``crack``):
  identical found sets, identical expanded-consumed totals, identical
  per-block ``on_batch`` sequences between the framed twins;
- RESUME — skip offsets at arbitrary (word x rule) positions — whole
  dropped blocks plus a mid-word straddler — interop bit-identically
  across all three entry points;
- FAULTS — a per-device stream crashing mid-flush requeues its rules
  block onto a survivor with found list, consumed total and demux
  order unchanged;
- CACHE — the ``.rbase`` base-block species: a warm replay serves
  pre-split ``RulesPrep`` blocks whose cracks are bit-identical to the
  cold run, including a warm index-seek resume.

One ESSID only (three nets share it) and every engine on a ONE-device
mesh (``_eng``), so the whole file compiles a single rules step — the
stream legs' inner engines are single-device by construction, and the
serial/flat legs reuse the same shape instead of paying a full-mesh
compile nothing else in tier-1 shares.  ``BATCH = 32`` matches
tests/test_streams.py so the plain single-device crack step is shared
too.  Lockstep full-mesh rules parity is tests/test_rules_device.py's
job.
"""

import gzip
import hashlib
import os

import jax
import pytest

from dwpa_tpu import testing as synth
from dwpa_tpu.feed import DictCache, RulesFeedSource, frame_blocks
from dwpa_tpu.models.m22000 import M22000Engine
from dwpa_tpu.parallel import default_mesh
from dwpa_tpu.rules import parse_rules

BATCH = 32
ESSID = b"MeshNet"
#: ':'/'u'/'c $1' expand on device; '@a' purges on the host interpreter
RULES = [":", "u", "c $1", "@a"]

PSK_U = b"MESHWORD77!"      # 'meshword77!' through 'u'  (word 5, block 0)
PSK_C = b"Meshtwo88!1"      # 'meshtwo88!' through 'c $1' (word 70, block 2)


def _lines():
    """Two crackable nets + one never cracked, all one ESSID: one PBKDF2
    group, one rules-step compile, and no early stop."""
    return [
        synth.make_pmkid_line(PSK_U, ESSID, seed="rm1"),
        synth.make_pmkid_line(PSK_C, ESSID, seed="rm2"),
        synth.make_pmkid_line(b"never-in-keyspace", ESSID, seed="rm3"),
    ]


def _words():
    """3 blocks of 32.  Word 40 is overlong (host fallback for ALL
    rules); word 41 is exactly 63 bytes, so 'c $1' overflows it into the
    per-pair host tail while ':'/'u' keep it on device."""
    words = [b"mshjunk%04d" % i for i in range(96)]
    words[5] = b"meshword77!"
    words[70] = b"meshtwo88!"
    words[40] = b"y" * 70
    words[41] = b"z" * 63
    return words


def _rules():
    return parse_rules(RULES)


def _eng(lines):
    """Single-device engine: one rules-step compile for the whole file
    (matches the stream legs' inner engines)."""
    return M22000Engine(lines, batch_size=BATCH,
                        mesh=default_mesh(devices=jax.devices()[:1]))


def _keys(founds):
    return sorted((f.line.essid, f.psk) for f in founds)


def _host_reference(lines, words, rules):
    """Pure host expansion — the pre-mesh-aggregate regime: interpret
    every (word, rule) pair on the host, then a plain dict crack."""
    cands = []
    for w in words:
        for r in rules:
            out = r.apply(w)
            if out is not None:
                cands.append(out)
    return _eng(lines).crack(iter(cands))


def test_rules_differential_parity():
    """All three device-expansion entry points equal the host-expansion
    reference, the framed twins share an identical per-block on_batch
    sequence, and every (word x rule) pair is consumed exactly once."""
    lines, words, rules = _lines(), _words(), _rules()
    exp_total = len(words) * len(rules)

    ref_founds = _host_reference(lines, words, rules)
    assert _keys(ref_founds) == [(ESSID, PSK_U), (ESSID, PSK_C)]

    flat_log = []
    flat = _eng(lines).crack_rules(
        iter(words), rules,
        on_batch=lambda c, f: flat_log.append(c))

    blk_log = []
    blk_eng = _eng(lines)
    blk = blk_eng.crack_rules_blocks(
        frame_blocks(iter(words), blk_eng.batch_size), rules,
        on_batch=lambda c, f: blk_log.append((c, sorted(x.psk for x in f))))

    st_log = []
    st_eng = _eng(lines)
    st = st_eng.crack_rules_streams(
        frame_blocks(iter(words), st_eng.batch_size), rules,
        on_batch=lambda c, f: st_log.append((c, sorted(x.psk for x in f))),
        devices=jax.devices()[:2])

    assert _keys(flat) == _keys(blk) == _keys(st) == _keys(ref_founds)
    # per-BLOCK framing identical between the serial and stream twins
    assert st_log == blk_log
    assert len(blk_log) == 3
    assert sum(c for c, _ in blk_log) == sum(flat_log) == exp_total
    # both engines pruned their live view down to the uncracked net
    assert len(blk_eng.nets) == len(st_eng.nets) == 1


@pytest.mark.parametrize("skip", [22, 263])
def test_rules_resume_skip_arbitrary_offsets(skip):
    """skip=22 straddles word 5 mid-expansion (a (word x rule) offset
    inside block 0); skip=263 drops blocks 0-1 whole (O(1), 256 pairs)
    and straddles block 2.  All three entry points cover the identical
    unskipped tail."""
    lines, words, rules = _lines(), _words(), _rules()
    exp_total = len(words) * len(rules)

    flat = _eng(lines).crack_rules(
        iter(words), rules, skip=skip)

    blk_log = []
    blk = _eng(lines).crack_rules_blocks(
        frame_blocks(iter(words), BATCH), rules, skip=skip,
        on_batch=lambda c, f: blk_log.append(c))

    st_log = []
    st = _eng(lines).crack_rules_streams(
        frame_blocks(iter(words), BATCH), rules, skip=skip,
        on_batch=lambda c, f: st_log.append(c),
        devices=jax.devices()[:2])

    assert _keys(flat) == _keys(blk) == _keys(st)
    assert sum(blk_log) == sum(st_log) == exp_total - skip
    if skip == 263:
        # blocks 0-1 fell inside the window: PSK_U (word 5) is skipped,
        # PSK_C (word 70, block 2) is still covered
        assert _keys(blk) == [(ESSID, PSK_C)]
        assert len(blk_log) == 1        # two whole blocks never framed
    else:
        assert _keys(blk) == [(ESSID, PSK_U), (ESSID, PSK_C)]


def test_rules_stream_crash_requeues_block():
    """Stream 0's first flush dies mid-wave: the rules block requeues
    onto the survivor, and founds / consumed total / per-block demux
    order all match a clean serial run."""
    lines, words, rules = _lines(), _words(), _rules()

    ref_log = []
    ref = _eng(lines).crack_rules_blocks(
        frame_blocks(iter(words), BATCH), rules,
        on_batch=lambda c, f: ref_log.append((c, sorted(x.psk for x in f))))

    booms = []

    def factory(device):
        eng = M22000Engine(lines, batch_size=BATCH,
                           mesh=default_mesh(devices=[device]))
        if device.id == jax.devices()[0].id:
            real = eng._rules_flush

            def flaky(*a, **k):
                if not booms:
                    booms.append(device.id)
                    raise RuntimeError("injected rules fault")
                return real(*a, **k)

            eng._rules_flush = flaky
        return eng

    st_log = []
    st = _eng(lines).crack_rules_streams(
        frame_blocks(iter(words), BATCH), rules,
        on_batch=lambda c, f: st_log.append((c, sorted(x.psk for x in f))),
        devices=jax.devices()[:2], engine_factory=factory)

    assert booms  # the fault actually fired
    assert _keys(st) == _keys(ref)
    assert st_log == ref_log


def test_rbase_warm_cold_parity(tmp_path):
    """The .rbase species: the cold run (tee write-back) and the warm
    replay (pre-split RulesPrep blocks) produce bit-identical block
    geometry, found lists and consumed totals; a warm base-word skip
    seeks the chunk index instead of replaying the gunzip stream."""
    lines, words, rules = _lines(), _words(), _rules()
    blob = gzip.compress(b"\n".join(words) + b"\n")
    path = os.path.join(str(tmp_path), "mesh.txt.gz")
    with open(path, "wb") as f:
        f.write(blob)
    dhash = hashlib.md5(blob).hexdigest()
    cache = DictCache(str(tmp_path / "cache"))
    units = [(path, dhash)]

    def run(skip_words=0):
        log = []
        src = RulesFeedSource(units, batch_size=BATCH, cache=cache,
                              skip=skip_words)
        founds = _eng(lines).crack_rules_blocks(
            iter(src), rules,
            on_batch=lambda c, f: log.append((c, sorted(x.psk for x in f))))
        return founds, log

    cold_founds, cold_log = run()
    assert cache.reader_rules(dhash) is not None  # tee committed
    warm_founds, warm_log = run()
    assert _keys(warm_founds) == _keys(cold_founds) \
        == [(ESSID, PSK_U), (ESSID, PSK_C)]
    assert warm_log == cold_log

    # warm pre-split blocks carry the RulesPrep marker end to end
    src = RulesFeedSource(units, batch_size=BATCH, cache=cache)
    blocks = list(src)
    # block counts are BASE words; on_batch reports the expanded domain
    assert [b.count * len(rules) for b in blocks] == [c for c, _ in cold_log]
    assert all(hasattr(b.prep, "rules_base") for b in blocks)

    # base-word skip past block 0: warm seek covers exactly the tail
    skip_founds, skip_log = run(skip_words=BATCH)
    assert _keys(skip_founds) == [(ESSID, PSK_C)]
    assert [c for c, _ in skip_log] == [c for c, _ in cold_log[1:]]
