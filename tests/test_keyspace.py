"""Smart-keyspace compiler + scheduler-helper unit tests.

Compiler property: every word a compiled mask enumerates fullmatches
the source pass-regex, and the compiled keyspace counts the language
EXACTLY — the loud-rejection contract's other half (what does compile
is bit-exact; what cannot be exact raises ``KeyspaceError``).
Plus the host odometer's parity against a per-index divmod oracle —
the generator every mask-resume proof in this repo leans on.
"""

import random
import re

import pytest

from dwpa_tpu.gen.mask import (mask_blocks, mask_digits_at, mask_keyspace,
                               mask_words, parse_mask)
from dwpa_tpu.keyspace import (CompiledKeyspace, KeyspaceError, MaskCache,
                               compile_pass_regex, ks_matches,
                               next_uncovered)


def _language(ck):
    """Every word every compiled mask enumerates (latin1 text)."""
    out = []
    for m in ck.masks:
        out += [w.decode("latin1")
                for w in mask_words(m.mask, m.custom_bytes())]
    return out


# ---------------------------------------------------------------------------
# compiler: exactness properties
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pattern", [
    r"^wifipass\d{2}$",
    r"TALK[0-9]{2}\d{2}",
    r"^[a-c]{1,3}X$",
    r"net\d{3}|wifi[xy]z",
    r"ab?c?d",
    r"\?\d{2}\\",          # escaped metacharacters as literals
    r"[0-9][a-z][A-F0-9]",  # builtin charsets by content
    r"pw[_\-.]\d",
])
def test_compiled_language_is_exact(pattern):
    ck = compile_pass_regex(pattern)
    words = _language(ck)
    # exact count: the summed mask keyspace IS the enumeration length
    assert len(words) == ck.keyspace
    # soundness: every enumerated word matches the source regex
    for w in words:
        assert re.fullmatch(pattern, w), (pattern, w)
    # masks don't overlap for these disjoint-branch patterns
    assert len(set(words)) == len(words)


def test_optional_atoms_expand_per_length():
    """``?`` = {0,1}: each length choice becomes its own mask, counts
    summing to the product of (1 + |alpha|) per optional atom."""
    ck = compile_pass_regex(r"a[bc]?[de]?")
    assert ck.keyspace == 1 + 2 + 2 + 4
    lengths = sorted(len(m.mask.replace("?1", "x").replace("?2", "x"))
                     for m in ck.masks)
    assert len(ck.masks) == 4
    words = _language(ck)
    assert sorted(words) == sorted(
        {w for w in ("a", "ab", "ac", "ad", "ae", "abd", "abe", "acd",
                     "ace")})
    assert lengths == sorted(lengths)


def test_masks_sorted_smallest_keyspace_first():
    """The compiler pre-sorts masks so mask_i ordering (and the
    scheduler's smallest-first issue order) is deterministic."""
    ck = compile_pass_regex(r"\d{4}|[ab]x|net[0-9a-f]{2}")
    sizes = [m.keyspace for m in ck.masks]
    assert sizes == sorted(sizes)
    assert sizes[0] == 2          # [ab]x
    assert sizes[-1] == 10000     # \d{4}


def test_builtin_charsets_recognized_by_content():
    ck = compile_pass_regex(r"[0-9][a-z][A-Z][0-9a-f]")
    assert [m.mask for m in ck.masks] == ["?d?l?u?h"]
    assert ck.masks[0].custom == {}


def test_custom_charsets_allocated_and_shared():
    ck = compile_pass_regex(r"[abc][xy][abc]")
    (m,) = ck.masks
    assert m.mask == "?1?2?1"     # repeated class reuses its slot
    assert m.custom == {"1": "abc", "2": "xy"}
    assert m.keyspace == 3 * 2 * 3
    # the bytes view parses through gen.mask with the same count
    assert mask_keyspace(m.mask, m.custom_bytes()) == m.keyspace


def test_literal_question_mark_escaped_for_hashcat():
    ck = compile_pass_regex(r"a\?b")
    (m,) = ck.masks
    assert m.mask == "a??b"
    assert [w for w in mask_words(m.mask, m.custom_bytes())] == [b"a?b"]


@pytest.mark.parametrize("pattern,reason_part", [
    (r"free.*", "'.'"),
    (r"a*", "unbounded"),
    (r"a+", "unbounded"),
    (r"(ab)c", "groups"),
    (r"(?=x)y", "groups"),
    (r"[^abc]", "negated"),
    (r"[b-a]", "reversed range"),
    (r"[]", "empty character class"),
    (r"[abc", "unterminated"),
    (r"a{2,1}", "reversed quantifier"),
    (r"a{", "unterminated"),
    (r"a{x}", "malformed"),
    (r"{3}", "without a free atom"),
    (r"a{2}?", "without a free atom"),   # stacked/lazy quantifier
    (r"?a", "without a free atom"),
    (r"a\w", "unsupported escape"),
    (r"a^b", "mid-pattern anchor"),
    (r"", "empty pattern"),
    (r"a|", "empty alternation branch"),
    (r"x?", "matches the empty string"),
    (r"\d{64}", "longer than 63"),
    (r"a?b?c?d?e?f?g?h?i?", "more than 64 masks"),
    ("p€ssword", "non-latin1"),
    (r"[ab][cd][ef][gh][ij]", "more than 4 custom charsets"),
])
def test_loud_rejection_never_silent_truncation(pattern, reason_part):
    with pytest.raises(KeyspaceError) as ei:
        compile_pass_regex(pattern)
    assert reason_part in ei.value.reason
    assert ei.value.pattern == pattern


def test_edge_anchors_accepted_and_dropped():
    for pat in (r"^ab$", r"ab", r"^ab", r"ab$"):
        ck = compile_pass_regex(pat)
        assert [m.mask for m in ck.masks] == ["ab"]
        assert ck.keyspace == 1


def test_alternation_split_respects_escapes_and_classes():
    ck = compile_pass_regex(r"a\|b|[x|y]")
    words = set(_language(ck))
    assert words == {"a|b", "x", "|", "y"}


# ---------------------------------------------------------------------------
# host odometer vs per-index divmod oracle
# ---------------------------------------------------------------------------


def _oracle_words(mask, custom, skip, limit):
    alphas = parse_mask(mask, custom)
    total = mask_keyspace(mask, custom)
    end = total if limit is None else min(total, skip + limit)
    out = []
    for idx in range(skip, end):
        digits = mask_digits_at(mask, idx, custom)
        out.append(bytes(alphas[p][digits[p]] for p in range(len(alphas))))
    return out


@pytest.mark.parametrize("mask,custom", [
    ("?d?d?d", None),
    ("a?l?d", None),
    ("?1?2?1", {"1": b"abc", "2": b"XY"}),
    ("x", None),
    ("", None),
])
def test_odometer_matches_divmod_oracle(mask, custom):
    rng = random.Random(1234)
    total = mask_keyspace(mask, custom)
    slices = [(0, None), (0, 1), (total, 5), (max(0, total - 1), None)]
    slices += [(rng.randrange(total + 1), rng.randrange(1, total + 2))
               for _ in range(8)]
    for skip, limit in slices:
        got = list(mask_words(mask, custom, skip=skip, limit=limit))
        assert got == _oracle_words(mask, custom, skip, limit), (skip, limit)


def test_mask_blocks_offsets_are_absolute_keyspace_indices():
    blocks = list(mask_blocks("?d?d?d", 128, skip=100, limit=300))
    assert [(b.offset, b.count) for b in blocks] == [
        (100, 128), (228, 128), (356, 44)]
    for b in blocks:
        assert b.words == [] and b.prep.mask_gen
        assert b.prep.start == b.offset


# ---------------------------------------------------------------------------
# scheduler helpers
# ---------------------------------------------------------------------------


def test_next_uncovered_walks_first_gap():
    ks = 100
    assert next_uncovered([], ks, 40) == (0, 40)
    cov = [{"skip": 0, "span": 40}]
    assert next_uncovered(cov, ks, 40) == (40, 40)
    # a reaped (DELETEd) middle range reappears as the first gap
    cov = [{"skip": 0, "span": 20}, {"skip": 60, "span": 40}]
    assert next_uncovered(cov, ks, 40) == (20, 40)
    # the gap bounds the issue even below span
    cov = [{"skip": 0, "span": 20}, {"skip": 30, "span": 70}]
    assert next_uncovered(cov, ks, 40) == (20, 10)
    # locally planned (not yet inserted) ranges count via ``extra``
    assert next_uncovered([], ks, 40, extra=[(0, 40), (40, 40)]) == (80, 20)
    cov = [{"skip": 0, "span": 100}]
    assert next_uncovered(cov, ks, 40) is None


def test_ks_matches_search_semantics_and_broken_rows():
    rows = [{"ssid_regex": r"^HOME-", "pass_regex": "x"},
            {"ssid_regex": r"NET", "pass_regex": "y"},
            {"ssid_regex": r"([", "pass_regex": "z"}]  # broken: skipped
    assert [r["pass_regex"] for r in ks_matches(rows, b"HOME-1234")] == ["x"]
    assert [r["pass_regex"] for r in ks_matches(rows, b"MYNETWORK")] == ["y"]
    assert ks_matches(rows, b"other") == []
    # latin1 ssid bytes decode, never raise
    assert ks_matches(rows, bytes(range(200, 210))) == []


def test_mask_cache_compiles_once_and_caches_misses():
    cache = MaskCache()
    ck = cache.get(r"^pw\d{2}$")
    assert isinstance(ck, CompiledKeyspace) and cache.compiles == 1
    assert cache.get(r"^pw\d{2}$") is ck     # warm: no recompile
    assert cache.compiles == 1
    assert cache.keyspace(r"^pw\d{2}$") == 100
    assert cache.get(r"bad(") is None        # uncompilable: cached miss
    assert cache.get(r"bad(") is None
    assert cache.keyspace(r"bad(") == 0
    assert cache.compiles == 1
