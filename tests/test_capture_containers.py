"""Capture-container and EAPOL-pairing coverage for server/capture.py —
the pcapng / radiotap / PPI / big-endian / M2+M3 / M3+M4 paths round 1
left untested (hcxpcapngtool parity surfaces)."""

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.models import hashline as hl
from dwpa_tpu.oracle import m22000 as oracle
from dwpa_tpu.server.capture import extract_hashlines, iter_frames

PSK = b"container-psk"
ESSID = b"ContainerNet"


def _lines_crack(blob, expected, psk=PSK):
    lines, _ = extract_hashlines(blob)
    assert len(lines) == expected
    for line in lines:
        assert oracle.check_key_m22000(hl.parse(line), [psk]) is not None
    return lines


FRAMES, EXPECTED = tfx.make_handshake_frames(PSK, ESSID, seed="cc")


# ---------------------------------------------------------------------------
# classic pcap variants


def test_bigendian_pcap():
    _lines_crack(tfx.pcap_bytes(FRAMES, endian=">"), EXPECTED)


def test_nanosecond_magic_pcap():
    _lines_crack(tfx.pcap_bytes(FRAMES, nsec=True), EXPECTED)
    _lines_crack(tfx.pcap_bytes(FRAMES, endian=">", nsec=True), EXPECTED)


# ---------------------------------------------------------------------------
# pcapng


def test_pcapng_epb_little_endian():
    _lines_crack(tfx.pcapng_bytes(FRAMES), EXPECTED)


def test_pcapng_epb_big_endian():
    _lines_crack(tfx.pcapng_bytes(FRAMES, endian=">"), EXPECTED)


def test_pcapng_simple_packet_blocks():
    _lines_crack(tfx.pcapng_bytes(FRAMES, simple=True), EXPECTED)


def test_pcapng_probes_survive():
    frames, _ = tfx.make_handshake_frames(
        PSK, ESSID, seed="ccpr", probes=(b"CafeWifi",)
    )
    _, probes = extract_hashlines(tfx.pcapng_bytes(frames))
    assert probes == [b"CafeWifi"]


# ---------------------------------------------------------------------------
# link-layer wrappers


def test_radiotap_frames():
    _lines_crack(tfx.pcap_bytes(tfx.radiotap_wrap(FRAMES), linktype=127), EXPECTED)


def test_radiotap_long_header():
    _lines_crack(
        tfx.pcap_bytes(tfx.radiotap_wrap(FRAMES, rt_len=24), linktype=127), EXPECTED
    )


def test_ppi_frames():
    _lines_crack(tfx.pcap_bytes(tfx.ppi_wrap(FRAMES), linktype=192), EXPECTED)


def test_unknown_linktype_skipped():
    lines, probes = extract_hashlines(tfx.pcap_bytes(FRAMES, linktype=1))
    assert lines == [] and probes == []


def test_truncated_container_tolerated():
    blob = tfx.pcap_bytes(FRAMES)
    lines, _ = extract_hashlines(blob[: len(blob) // 2])
    assert isinstance(lines, list)  # no crash on truncation


# ---------------------------------------------------------------------------
# M2+M3 and M3+M4 pairings (message_pair 2 / 3, common.php:114-155)


def _paired_capture(seed, sta_msgs, ap_replay, sta_replay, m4_snonce=True):
    """Build a capture holding an M3 plus the given STA message."""
    mac_ap = tfx._rand(seed + "ap", 6)
    mac_sta = tfx._rand(seed + "sta", 6)
    anonce = tfx._rand(seed + "anonce", 32)
    snonce = tfx._rand(seed + "snonce", 32)
    pmk = oracle.pmk_from_psk(PSK, ESSID)

    # the STA frame whose MIC lands in the hashline
    ki_sta = 0x010A if sta_msgs == 2 else 0x030A
    sn = snonce if (sta_msgs == 2 or m4_snonce) else b"\x00" * 32
    zero = tfx.build_eapol_key_frame(ki_sta, sta_replay, sn,
                                     key_data=tfx._rand(seed + "kd", 22))
    m = min(mac_ap, mac_sta) + max(mac_ap, mac_sta)
    n = snonce + anonce if snonce[:6] < anonce[:6] else anonce + snonce
    mic = oracle.compute_mic(pmk, 2, m, n, zero)
    sta_frame = zero[:81] + mic + zero[97:]

    m3 = tfx.build_eapol_key_frame(0x13CA, ap_replay, anonce)
    frames = [
        tfx.beacon_frame(mac_ap, ESSID),
        tfx._dot11_data_eapol(mac_ap, mac_sta, mac_ap, m3, from_ds=True),
        tfx._dot11_data_eapol(mac_sta, mac_ap, mac_ap, sta_frame, from_ds=False),
    ]
    return tfx.pcap_bytes(frames)


def test_m2_m3_pairing():
    # M3 replay = M2 replay + 1 (the authenticated-ANONCE pairing)
    blob = _paired_capture("p23", sta_msgs=2, ap_replay=2, sta_replay=1)
    lines = _lines_crack(blob, 1)
    assert hl.parse(lines[0]).message_pair & 0x07 == 0x02


def test_m3_m4_pairing():
    blob = _paired_capture("p34", sta_msgs=4, ap_replay=2, sta_replay=2)
    lines = _lines_crack(blob, 1)
    assert hl.parse(lines[0]).message_pair & 0x07 == 0x03


def test_m4_zero_snonce_not_paired():
    # an M4 with an all-zero SNONCE cannot derive the PTK; no hashline
    blob = _paired_capture("p34z", sta_msgs=4, ap_replay=2, sta_replay=2,
                           m4_snonce=False)
    lines, _ = extract_hashlines(blob)
    assert lines == []


def test_malformed_pcapng_blocks_tolerated():
    """Empty IDB/SPB bodies must be skipped, not crash extraction."""
    import struct

    def block(btype, body):
        total = 12 + len(body) + (-len(body)) % 4
        return (struct.pack("<II", btype, total) + body
                + b"\x00" * ((-len(body)) % 4) + struct.pack("<I", total))

    shb = block(0x0A0D0D0A, struct.pack("<I", 0x1A2B3C4D) + struct.pack("<HHq", 1, 0, -1))
    bad = shb + block(1, b"") + block(3, b"") + block(6, b"\x00" * 8)
    lines, probes = extract_hashlines(bad)
    assert lines == [] and probes == []


# ---------------------------------------------------------------------------
# nonce-increment endianness hints (MP_LE/MP_BE, hcxpcapngtool behavior)


def _retrans_capture(seed, endian="<", delta=1):
    """M1(replay1, anonce) + M1(replay2, anonce+delta) + M2: a router
    that increments its ANONCE between retransmissions."""
    import struct

    mac_ap = tfx._rand(seed + "ap", 6)
    mac_sta = tfx._rand(seed + "sta", 6)
    anonce = tfx._rand(seed + "anonce", 32)
    snonce = tfx._rand(seed + "snonce", 32)
    pmk = oracle.pmk_from_psk(PSK, ESSID)
    last = struct.unpack(endian + "I", anonce[28:])[0]
    anonce2 = anonce[:28] + struct.pack(endian + "I", (last + delta) & 0xFFFFFFFF)

    zero = tfx.build_eapol_key_frame(0x010A, 1, snonce,
                                     key_data=tfx._rand(seed + "kd", 22))
    m = min(mac_ap, mac_sta) + max(mac_ap, mac_sta)
    n = snonce + anonce if snonce[:6] < anonce[:6] else anonce + snonce
    mic = oracle.compute_mic(pmk, 2, m, n, zero)
    m2 = zero[:81] + mic + zero[97:]

    frames = [
        tfx.beacon_frame(mac_ap, ESSID),
        tfx._dot11_data_eapol(mac_ap, mac_sta, mac_ap,
                              tfx.build_eapol_key_frame(0x008A, 1, anonce),
                              from_ds=True),
        tfx._dot11_data_eapol(mac_ap, mac_sta, mac_ap,
                              tfx.build_eapol_key_frame(0x008A, 2, anonce2),
                              from_ds=True),
        tfx._dot11_data_eapol(mac_sta, mac_ap, mac_ap, m2, from_ds=False),
    ]
    return tfx.pcap_bytes(frames)


def test_le_increment_sets_le_hint():
    lines = _lines_crack(_retrans_capture("le1", endian="<"), 1)
    mp = hl.parse(lines[0]).message_pair
    assert mp & hl.MP_LE and not mp & hl.MP_BE


def test_be_increment_sets_be_hint():
    lines = _lines_crack(_retrans_capture("be1", endian=">", delta=3), 1)
    mp = hl.parse(lines[0]).message_pair
    assert mp & hl.MP_BE


def test_no_retransmission_no_hint():
    lines = _lines_crack(tfx.pcap_bytes(FRAMES), EXPECTED)
    for line in lines:
        h = hl.parse(line)
        if h.hash_type == hl.TYPE_EAPOL:
            assert not h.message_pair & (hl.MP_LE | hl.MP_BE)


def test_parser_survives_garbage_and_mutations():
    """Ingestion is an open endpoint: random blobs and bit-flipped valid
    captures must parse to (possibly empty) results, never raise."""
    import random

    rng = random.Random(0xFEED)
    cap = tfx.pcap_bytes(FRAMES)
    blobs = [bytes(rng.randrange(256) for _ in range(n))
             for n in (0, 1, 7, 64, 300)]
    for _ in range(40):
        mut = bytearray(cap)
        for _ in range(rng.randrange(1, 8)):
            mut[rng.randrange(len(mut))] ^= 1 << rng.randrange(8)
        blobs.append(bytes(mut))
    for i in range(12):
        cut = rng.randrange(len(cap))
        blobs.append(cap[:cut])                      # truncations
        blobs.append(cap + cap[:cut])                # trailing junk
    for blob in blobs:
        try:
            lines, probes = extract_hashlines(blob)
        except ValueError:
            lines = []  # "not a capture" is the endpoint's 400 contract
        for ln in lines:
            hl.parse(ln)                             # anything emitted parses
    # (the native parser gets the same blobs differentially in
    # tests/test_native_capture.py's fuzz loops)


# ---------------------------------------------------------------------------
# --eapoltimeout pairing gate (web/common.php:481)


def _interleaved_sessions_frames(seed="eto"):
    """Two handshake sessions, same (ap, sta), same replay counter, far
    apart in time.  Session A contributes only an M1 (anonce_a); session
    B is complete (M1 anonce_b + M2 whose MIC is real over anonce_b).
    An ungated parser pairs B's M2 with A's M1 — first in _PAIRINGS scan
    order — and emits a line whose MIC can never verify."""
    mac_ap = tfx._rand(seed + "ap", 6)
    mac_sta = tfx._rand(seed + "sta", 6)
    anonce_a = tfx._rand(seed + "anonceA", 32)
    anonce_b = tfx._rand(seed + "anonceB", 32)
    snonce = tfx._rand(seed + "snonce", 32)
    pmk = oracle.pmk_from_psk(PSK, ESSID)

    m1_a = tfx.build_eapol_key_frame(0x008A, 1, anonce_a)
    m1_b = tfx.build_eapol_key_frame(0x008A, 1, anonce_b)
    m2_zero = tfx.build_eapol_key_frame(0x010A, 1, snonce,
                                        key_data=tfx._rand(seed + "rsn", 22))
    m = min(mac_ap, mac_sta) + max(mac_ap, mac_sta)
    n = snonce + anonce_b if snonce[:6] < anonce_b[:6] else anonce_b + snonce
    mic = oracle.compute_mic(pmk, 2, m, n, m2_zero)
    m2 = m2_zero[:81] + mic + m2_zero[97:]

    frames = [
        tfx.beacon_frame(mac_ap, ESSID),
        tfx._dot11_data_eapol(mac_ap, mac_sta, mac_ap, m1_a, from_ds=True),
        tfx._dot11_data_eapol(mac_ap, mac_sta, mac_ap, m1_b, from_ds=True),
        tfx._dot11_data_eapol(mac_sta, mac_ap, mac_ap, m2, from_ds=False),
    ]
    t0 = 1700000000
    times = [t0, t0, t0 + 100.0, t0 + 100.5]  # A's M1 100 s before B
    return frames, times, anonce_b


def test_eapoltimeout_rejects_cross_session_pairing():
    frames, times, anonce_b = _interleaved_sessions_frames()
    lines, _ = extract_hashlines(tfx.pcap_bytes(frames, times=times))
    eapols = [hl.parse(x) for x in lines
              if hl.parse(x).hash_type == hl.TYPE_EAPOL]
    # Exactly one line, paired within the same session: crackable.
    assert len(eapols) == 1
    assert eapols[0].anonce == anonce_b
    assert oracle.check_key_m22000(eapols[0], [PSK]) is not None


def test_eapoltimeout_disabled_shows_the_junk_line():
    """Sanity check on the fixture: with the gate off, the scan pairs
    A's stale M1 first and the emitted line is uncrackable junk."""
    frames, times, anonce_b = _interleaved_sessions_frames()
    lines, _ = extract_hashlines(tfx.pcap_bytes(frames, times=times),
                                 eapol_timeout=float("inf"))
    eapols = [hl.parse(x) for x in lines
              if hl.parse(x).hash_type == hl.TYPE_EAPOL]
    assert len(eapols) == 1
    assert eapols[0].anonce != anonce_b
    assert oracle.check_key_m22000(eapols[0], [PSK]) is None


def test_eapoltimeout_pcapng_and_native_agree():
    """Differential: the C++ twin applies the identical gate, in both
    containers (pcapng EPB timestamps use if_tsresol units)."""
    from dwpa_tpu import native

    if native.load() is None:
        import pytest

        pytest.skip("native capture library unavailable")
    frames, times, _ = _interleaved_sessions_frames()
    for blob in (tfx.pcap_bytes(frames, times=times),
                 tfx.pcap_bytes(frames, times=times, nsec=True, endian=">"),
                 tfx.pcapng_bytes(frames, times=times)):
        py = extract_hashlines(blob)
        assert native.extract_hashlines_fast(blob) == py
        py_off = extract_hashlines(blob, eapol_timeout=1e9)
        assert native.extract_hashlines_fast(blob, eapol_timeout=1e9) == py_off
        assert py != py_off  # the gate actually changed the outcome


def test_pcapng_truncated_tsresol_option_no_crash():
    """An IDB whose if_tsresol option header declares a value byte the
    body doesn't contain must parse to nothing, not crash (hostile
    uploads reach this parser unauthenticated) — and the native twin
    must agree."""
    import struct as st

    from dwpa_tpu import native

    def block(btype, body):
        pad = (-len(body)) % 4
        total = 12 + len(body) + pad
        return (st.pack("<II", btype, total) + body + b"\x00" * pad
                + st.pack("<I", total))

    shb = block(0x0A0D0D0A, st.pack("<I", 0x1A2B3C4D) + st.pack("<HHq", 1, 0, -1))
    idb = block(0x00000001,
                st.pack("<HHI", 105, 0, 65535) + st.pack("<HH", 9, 1))
    blob = shb + idb
    assert extract_hashlines(blob) == ([], [])
    if native.load() is not None:
        assert native.extract_hashlines_fast(blob) == ([], [])
