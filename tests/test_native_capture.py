"""Differential tests: the C++ bulk capture parser (native/capture_fast)
must produce byte-identical output to the Python specification parser
(server/capture.py) on every container and pairing variant."""

import shutil

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.models import hashline as hl
from dwpa_tpu.server.capture import extract_hashlines

native = pytest.importorskip("dwpa_tpu.native")

if shutil.which("g++") is None or native.load() is None:
    pytest.skip("native toolchain unavailable", allow_module_level=True)

PSK = b"native-psk-22"
ESSID = b"NativeDiffNet"


def _diff(blob, nc_hint=True):
    fast = native.extract_hashlines_fast(blob, nc_hint=nc_hint)
    py = extract_hashlines(blob, nc_hint=nc_hint)
    assert fast == py
    for ln in py[0]:
        hl.parse(ln)  # anything either parser emits must be a valid line
    return py


FRAMES, _ = tfx.make_handshake_frames(PSK, ESSID, seed="nd",
                                      probes=(b"net-one", b"net-two"))


@pytest.mark.parametrize("wrap", [
    lambda f: tfx.pcap_bytes(f),
    lambda f: tfx.pcap_bytes(f, endian=">"),
    lambda f: tfx.pcap_bytes(f, nsec=True),
    lambda f: tfx.pcapng_bytes(f),
    lambda f: tfx.pcapng_bytes(f, endian=">"),
    lambda f: tfx.pcapng_bytes(f, simple=True),
    lambda f: tfx.pcap_bytes(tfx.radiotap_wrap(f), linktype=127),
    lambda f: tfx.pcap_bytes(tfx.radiotap_wrap(f, rt_len=24), linktype=127),
    lambda f: tfx.pcap_bytes(tfx.ppi_wrap(f), linktype=192),
], ids=["pcap-le", "pcap-be", "pcap-nsec", "pcapng-le", "pcapng-be",
        "pcapng-spb", "radiotap", "radiotap24", "ppi"])
def test_every_container_matches(wrap):
    lines, probes = _diff(wrap(FRAMES))
    assert len(lines) == 2 and len(probes) == 2


def test_nc_hint_off_matches():
    lines, _ = _diff(tfx.pcap_bytes(FRAMES), nc_hint=False)
    assert any(l.split("*")[1] == "02" for l in lines)


def test_multi_network_capture_matches():
    frames = []
    for i in range(4):
        fr, _ = tfx.make_handshake_frames(
            b"psk-%d-multi" % i, b"MultiNet%d" % i, seed="m%d" % i,
            with_pmkid=(i % 2 == 0), probes=(b"probe%d" % i,),
        )
        frames += fr
    lines, probes = _diff(tfx.pcap_bytes(frames))
    assert len(lines) == 6 and len(probes) == 4  # 4 EAPOL + 2 PMKID


def test_truncation_fuzz_matches():
    """Every truncation point of a real capture must parse identically
    (malformed tails are where hand-rolled parsers diverge)."""
    blob = tfx.pcap_bytes(FRAMES)
    for cut in range(0, len(blob), 7):
        try:
            py = extract_hashlines(blob[:cut])
        except ValueError:
            # python rejects unrecognizable stubs; native must yield nothing
            assert native.extract_hashlines_fast(blob[:cut]) == ([], [])
            continue
        assert native.extract_hashlines_fast(blob[:cut]) == py


def test_bitflip_fuzz_matches():
    import random

    rng = random.Random(42)
    base = bytearray(tfx.pcapng_bytes(FRAMES))
    for _ in range(200):
        blob = bytearray(base)
        for _ in range(rng.randrange(1, 6)):
            blob[rng.randrange(len(blob))] ^= 1 << rng.randrange(8)
        try:
            py = extract_hashlines(bytes(blob))
        except Exception:
            continue  # python parser raised; native behavior unspecified
        fast = native.extract_hashlines_fast(bytes(blob))
        assert fast == py


def test_garbage_input():
    assert native.extract_hashlines_fast(b"") == ([], [])
    assert native.extract_hashlines_fast(b"\x00" * 64) == ([], [])
    assert native.extract_hashlines_fast(b"\x0a\x0d\x0d\x0a" + b"\xff" * 60) == ([], [])


def test_bulk_throughput_exceeds_python():
    """The fast path must beat the Python parser on a bulk re-parse
    (its reason to exist: fill_pr/enrich over archived submissions).

    Interleaved best-of-N: a single back-to-back wall-clock A/B on a
    loaded 2-core host is a coin flip (the round-2 suite's one flake);
    comparing the *floors* of interleaved samples is deterministic as
    long as the native parser is genuinely faster, which it is by an
    order of magnitude."""
    import time

    blob = tfx.pcap_bytes(FRAMES * 200)
    t_fast = t_py = float("inf")
    for _ in range(7):
        t0 = time.perf_counter()
        native.extract_hashlines_fast(blob)
        t_fast = min(t_fast, time.perf_counter() - t0)
        t0 = time.perf_counter()
        extract_hashlines(blob)
        t_py = min(t_py, time.perf_counter() - t0)
    assert t_fast < t_py


def test_endian_hint_matches():
    from test_capture_containers import _retrans_capture

    for kw in ({"endian": "<"}, {"endian": ">"}, {"endian": "<", "delta": 200}):
        _diff(_retrans_capture("nd-eh", **kw))
