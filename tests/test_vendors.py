"""Vendor default-key generators (gen/vendors.py) — the routerkeygen-cli
equivalent (web/rkg.php:109) — plus their keygen-precompute wiring."""

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.gen import vendors as V
from dwpa_tpu.server.core import ServerCore
from dwpa_tpu.server.db import Database
from dwpa_tpu.server.jobs import keygen_precompute


@pytest.fixture
def core(tmp_path):
    db = Database(":memory:")
    return ServerCore(db, dictdir=str(tmp_path / "dicts"), capdir=str(tmp_path / "caps"))


# ---------------------------------------------------------------------------
# Thomson / SpeedTouch


def test_thomson_key_shape_and_search():
    sfx, key = V.thomson_key(V._thomson_serial(7, 34, "ABC"))
    assert len(sfx) == 6 and len(key) == 10
    found = list(V.thomson_candidates(sfx, years=[7], weeks=[34], device=False))
    assert key in found


def test_thomson_device_sweep_matches_hashlib():
    # The accelerator sweep (rolled compression on CPU) must find the same
    # candidates the hashlib reference search does.
    sfx, key = V.thomson_key(V._thomson_serial(9, 12, "Z1Q"))
    dev = set(V._thomson_search_device(sfx, [9], [12]))
    ref = set(V.thomson_candidates(sfx, years=[9], weeks=[12], device=False))
    assert key in dev
    assert dev == ref


def test_thomson_ssid_dispatch():
    sfx, key = V.thomson_key(V._thomson_serial(6, 2, "7F0"))
    pairs = list(
        V.vendor_candidates(
            b"\x00\x01\x02\x03\x04\x05",
            b"SpeedTouch" + sfx.encode(),
            thomson_kw={"years": [6], "weeks": [2], "device": False},
        )
    )
    assert ("Thomson", key) in pairs


# ---------------------------------------------------------------------------
# Belkin


def test_belkin_fixture():
    keys = list(V.belkin_keys(bytes.fromhex("001122334455")))
    # hand-derived: tail nibbles "22334455" through order (6,2,3,8,5,1,7,4)
    # over charset "024613578ACE9BDF"
    assert keys[0] == b"14631436"
    assert len(keys) == 4  # WAN-MAC offsets 0, +1, +2, -1
    assert all(len(k) == 8 and set(k) <= set(b"024613578ACE9BDF") for k in keys)


# ---------------------------------------------------------------------------
# EasyBox


def test_easybox_fixture():
    keys = list(V.easybox_keys(bytes.fromhex("001A2B3C4D5E")))
    # hand-derived for tail 4D5E: sn=19806, k1=13, k2=9
    assert keys[0] == b"B43DC7574"
    assert all(len(k) == 9 for k in keys)


# ---------------------------------------------------------------------------
# MAC-tail and IMEI families


def test_mac_tail_keys():
    base = int("c83a35f0e1d2", 16)
    keys = list(V.mac_tail_keys(bytes.fromhex("c83a35f0e1d2")))
    assert str(base % 10 ** 8).zfill(8).encode() in keys
    assert str((base + 1) % 10 ** 10).zfill(10).encode() in keys
    # hex tails belong to the Single generator; no duplicates here
    assert all(k.isdigit() for k in keys)


def test_imei_hotspot_bounded():
    keys = list(V.imei_hotspot_keys(limit_per_tac=5))
    assert len(keys) == 5 * len(V.HOTSPOT_TACS)
    assert all(len(k) == 8 and k.isdigit() for k in keys)


# ---------------------------------------------------------------------------
# keygen_precompute wiring (vendor algos are the default extra generators)


def test_precompute_cracks_belkin_default(core):
    bssid = bytes.fromhex("94103E7A1B2C")
    key = list(V.belkin_keys(bssid))[0]
    line = tfx.make_pmkid_line(key, b"Belkin.7A1B2C", seed="vbk", mac_ap=bssid)
    core.add_hashlines([line])
    stats = keygen_precompute(core)
    assert stats["cracked"] == 1
    row = core.db.q1("SELECT * FROM nets")
    assert row["n_state"] == 1 and row["pass"] == key and row["algo"] == "Belkin"


def test_precompute_cracks_easybox_default(core):
    bssid = bytes.fromhex("001A2B3C4D5E")
    key = list(V.easybox_keys(bssid))[0]
    line = tfx.make_eapol_line(
        key, b"EasyBox-3C4D5E", keyver=2, seed="veb", mac_ap=bssid
    )
    core.add_hashlines([line])
    stats = keygen_precompute(core)
    assert stats["cracked"] == 1
    row = core.db.q1("SELECT * FROM nets")
    assert row["algo"] == "EasyBox"
    # the full candidate log landed in rkg, reference wpa.sql:250-258
    assert core.db.q1(
        "SELECT COUNT(*) c FROM rkg WHERE algo = 'EasyBox'")["c"] >= 1


def test_precompute_cracks_mac_tail_default(core):
    bssid = bytes.fromhex("c83a35f0e1d2")
    # the decimalized-MAC key: only the MacTail family generates it (the
    # hex tails are also covered by the Single generator, which runs first)
    key = str(int.from_bytes(bssid, "big") % 10 ** 8).zfill(8).encode()
    line = tfx.make_pmkid_line(key, b"Tenda_F0E1D2", seed="vmt", mac_ap=bssid)
    core.add_hashlines([line])
    stats = keygen_precompute(core)
    assert stats["cracked"] == 1
    assert core.db.q1("SELECT algo FROM nets")["algo"] == "MacTail"


# ---------------------------------------------------------------------------
# WPS-PIN default-key family


def test_wps_checksum_is_valid_wsc():
    from dwpa_tpu.gen.vendors import wps_checksum_digit

    # WSC §7.4.1 validity: 3*(d1+d3+d5+d7) + (d2+d4+d6+d8) ≡ 0 (mod 10)
    for pin7 in (1234567, 0, 9999999, 2017480):
        pin = pin7 * 10 + wps_checksum_digit(pin7)
        digits = [int(c) for c in "%08d" % pin]
        acc = 3 * sum(digits[0::2]) + sum(digits[1::2])
        assert acc % 10 == 0, pin


def test_wps_pin_keys_shape_and_mac_derivation():
    from dwpa_tpu.gen.vendors import wps_pin_keys

    bssid = bytes.fromhex("c83a35123456")
    keys = list(wps_pin_keys(bssid))
    assert all(len(k) == 8 and k.isdigit() for k in keys)
    # the zero-delta pin embeds mac[3:] % 10^7 as its data digits
    assert keys[0][:7] == b"%07d" % (0x123456 % 10_000_000)
    assert b"12345670" in keys  # static factory pin rides along


def test_wps_pin_net_cracked_by_precompute():
    from dwpa_tpu.gen.vendors import wps_pin_keys
    from dwpa_tpu.server.core import ServerCore
    from dwpa_tpu.server.db import Database
    from dwpa_tpu.server.jobs import keygen_precompute
    from dwpa_tpu import testing as tfx

    bssid = bytes.fromhex("c83a35123456")
    psk = list(wps_pin_keys(bssid))[0]
    line = tfx.make_pmkid_line(psk, b"TP-LINK_123456", seed="wps1",
                               mac_ap=bssid)
    core = ServerCore(Database(":memory:"))
    core.add_hashlines([line])
    out = keygen_precompute(core)
    assert out["cracked"] == 1
    net = core.db.q1("SELECT algo, pass FROM nets")
    assert net["algo"] == "WPSPin" and net["pass"] == psk


# ---------------------------------------------------------------------------
# Round-3 families: Zyxel / Sky / Comtrend / Eircom / Alice AGPF / MacFull.
# One pinned (ssid, bssid) -> key vector per family; vectors are
# generated by this implementation of the published schemes (no network
# to cross-check the original tools — see the module fidelity note) and
# pin the derivations against regression.

BSSID = bytes.fromhex("0013F7A4B8C2")


def test_zyxel_kat_and_dispatch():
    keys = list(V.zyxel_keys(BSSID))
    assert keys[0] == b"E778B22FBAA6D370B515"  # md5("0013F7A4B8C2")[:20]
    assert len(keys) == 3 and len(set(keys)) == 3
    pairs = list(V.vendor_candidates(BSSID, b"ZyXELA4B8C2"))
    assert ("Zyxel", keys[0]) in pairs


def test_sky_kat_and_dispatch():
    keys = list(V.sky_keys(BSSID))
    assert keys[0] == b"XQWVEKDI"
    assert all(len(k) == 8 and k.isalpha() and k.isupper() for k in keys)
    assert ("Sky", keys[0]) in V.vendor_candidates(BSSID, b"SKY12345")
    assert not list(V.vendor_candidates(BSSID, b"SKY1234"))  # 5 digits only


def test_comtrend_kat_and_dispatch():
    keys = list(V.comtrend_keys(BSSID, "1A2B"))
    assert keys[0] == b"38d77c2302d8ec839174"
    assert ("Comtrend", keys[0]) in V.vendor_candidates(BSSID, b"WLAN_1A2B")
    assert ("Comtrend", keys[0]) in V.vendor_candidates(BSSID, b"JAZZTEL_1a2b")


def test_eircom_kat_and_dispatch():
    keys = list(V.eircom_keys(BSSID))
    assert keys[0] == b"93deacb33feb44c24d9ebd1713"
    assert all(len(k) == 26 for k in keys)
    assert ("Eircom", keys[0]) in V.vendor_candidates(BSSID, b"eircom2633 7724")
    assert ("Eircom", keys[0]) in V.vendor_candidates(BSSID, b"eircom26337724")


def test_alice_agpf_core_kat():
    key = V.alice_agpf_key("69102X0013305", BSSID)
    assert key == b"bruvns9exgnnmjcavoausk51"
    assert len(key) == 24 and all(c in b"0123456789abcdefghijklmnopqrstuvwxyz"
                                  for c in key)


def test_alice_agpf_config_dispatch():
    cfg = {"96": [{"sn": "69102", "q": 60, "k": 8}]}
    # (96013364 - 60) / 8 = 12001663 -> serial 69102X12001663
    keys = list(V.alice_agpf_keys("96013364", BSSID, configs=cfg))
    assert keys[0] == b"wcbvyfkrtw5ffhunjbubujxx"
    # non-divisible SSIDs produce nothing from that entry
    assert list(V.alice_agpf_keys("96013365", BSSID, configs=cfg)) == []
    # without deployment config tables the family is silent, not wrong
    assert list(V.vendor_candidates(BSSID, b"Alice-96013364")) == []
    pairs = list(V.vendor_candidates(BSSID, b"Alice-96013364",
                                     alice_configs=cfg))
    assert ("AliceAGPF", keys[0]) in pairs


def test_mac_full_kat_and_dispatch():
    keys = list(V.mac_full_keys(BSSID))
    assert keys[0] == b"0013f7a4b8c2"
    assert b"0013F7A4B8C2" in keys and b"13f7a4b8c2" in keys
    assert ("MacFull", keys[0]) in V.vendor_candidates(BSSID, b"CVTV12345")
    assert ("MacFull", keys[0]) in V.vendor_candidates(BSSID, b"Megared1A2B")


def test_family_count_at_least_twelve():
    """The dispatcher covers >= 12 distinct vendor families (VERDICT r2
    asked for breadth toward routerkeygen-cli's dozens)."""
    import re as _re

    src = open(V.__file__).read()
    algos = set(_re.findall(r'yield \("([A-Za-z]+)",', src))
    assert len(algos) >= 12, sorted(algos)
