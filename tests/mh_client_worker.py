"""Subprocess worker for the multi-host CLIENT test (not collected by
pytest).  Joins the two-process jax.distributed cluster and runs a full
``TpuCrackClient`` volunteer loop: process 0 fetches/submits over the
real socket server started by the parent test, process 1 receives the
unit only through the client's broadcast layer — the "multi-host slice
as ONE very large volunteer" contract (client/main.py run())."""

import os
import sys


def main():
    pid = int(sys.argv[1])
    coord_port = sys.argv[2]
    http_port = sys.argv[3]
    workdir = sys.argv[4]
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax

    jax.config.update("jax_platforms", "cpu")

    from dwpa_tpu.utils.compcache import enable_compilation_cache

    enable_compilation_cache(os.path.join(
        os.path.dirname(__file__), "..", ".pytest_xla_cache"))

    from dwpa_tpu.parallel.mesh import multihost_mesh

    multihost_mesh(coordinator=f"localhost:{coord_port}",
                   num_processes=2, process_id=pid)
    assert jax.process_count() == 2

    import dwpa_tpu
    import dwpa_tpu.client.main as cm
    from dwpa_tpu.client.main import ClientConfig, TpuCrackClient

    if len(sys.argv) > 5 and sys.argv[5]:
        # simulate a host running a different client build (the
        # mixed-version negative test): the slice must refuse to start
        dwpa_tpu.__version__ = cm.__version__ = sys.argv[5]

    cfg = ClientConfig(
        base_url=f"http://127.0.0.1:{http_port}/",
        workdir=os.path.join(workdir, f"host{pid}"),
        max_work_units=1, batch_size=128,
    )
    client = TpuCrackClient(
        cfg, log=lambda *a: print(f"[{pid}]", *a, flush=True))
    n = client.run()
    pot = ""
    if os.path.exists(client.potfile):
        pot = open(client.potfile).read().strip()
    print(f"MHCLIENT {pid} done={n} pot={'yes' if pot else 'no'}", flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
