"""Server stack tests: capture parsing, ingestion, scheduler, acceptance,
jobs — mirroring the reference's runtime guarantees (SURVEY.md §4): the
server never trusts client output (independent re-verification), leases
are reaped, coverage is never double-issued.
"""

import gzip
import hashlib
import json
import io

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.models import hashline as hl
from dwpa_tpu.oracle import m22000 as oracle
from dwpa_tpu.server import Database, ServerCore, make_wsgi_app
from dwpa_tpu.server.api import submit_capture
from dwpa_tpu.server.capture import extract_hashlines
from dwpa_tpu.server.jobs import (
    keygen_precompute,
    maintenance,
    single_mode_candidates,
)

PSK = b"correct-battery"
ESSID = b"TestLanParty"


@pytest.fixture
def core(tmp_path):
    db = Database(":memory:")
    return ServerCore(db, dictdir=str(tmp_path / "dicts"), capdir=str(tmp_path / "caps"))


def _add_dict(core, words, name="small.txt.gz", rules=None):
    import os
    os.makedirs(core.dictdir, exist_ok=True)
    blob = gzip.compress(b"\n".join(words) + b"\n")
    path = f"{core.dictdir}/{name}"
    with open(path, "wb") as f:
        f.write(blob)
    dhash = hashlib.md5(blob).hexdigest()
    core.add_dict(f"dict/{name}", name, dhash, len(words), rules=rules)
    return dhash


# -- capture parsing -------------------------------------------------------


def test_extract_hashlines_from_pcap():
    blob, expected = tfx.make_handshake_capture(
        PSK, ESSID, probes=[b"CoffeeShop", b"Airport-Free"]
    )
    lines, probes = extract_hashlines(blob)
    assert len(lines) == expected == 2
    assert probes == [b"CoffeeShop", b"Airport-Free"]
    kinds = sorted(hl.parse(l).hash_type for l in lines)
    assert kinds == [hl.TYPE_PMKID, hl.TYPE_EAPOL]
    # the extracted lines must verify against the real PSK (oracle = spec)
    for line in lines:
        assert oracle.check_key_m22000(line, [PSK]) is not None, line


def test_extracted_eapol_is_m1m2_pair():
    blob, _ = tfx.make_handshake_capture(PSK, ESSID, with_pmkid=False)
    lines, _ = extract_hashlines(blob)
    assert len(lines) == 1
    h = hl.parse(lines[0])
    assert h.message_pair & 0x07 == 0  # M1+M2 encoding
    assert h.keyver == 2


# -- ingestion -------------------------------------------------------------


def test_submission_pipeline(core):
    blob, expected = tfx.make_handshake_capture(PSK, ESSID, probes=[b"HomeBox"])
    report = submit_capture(core, blob, ip="1.2.3.4")
    assert report["new"] == expected
    assert report["probes"] == 1
    # duplicate upload: same capture md5 -> same submission, nets deduped
    report2 = submit_capture(core, blob)
    assert report2["new"] == 0 and report2["dup"] == expected
    assert core.db.q1("SELECT COUNT(*) c FROM submissions")["c"] == 1
    # bssids auto-populated by trigger
    assert core.db.q1("SELECT COUNT(*) c FROM bssids")["c"] == 1


def test_ingest_cross_crack(core):
    """A new net whose sibling (same SSID) is already cracked gets the PMK
    replayed at ingest time and arrives pre-cracked."""
    l1 = tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="cc1")
    core.add_hashlines([l1])
    net = core.db.q1("SELECT * FROM nets")
    core._try_accept(net, PSK)
    assert core.db.q1("SELECT n_state FROM nets")["n_state"] == 1

    l2 = tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="cc2")
    report = core.add_hashlines([l2])
    assert report["precracked"] == 1
    states = [r["n_state"] for r in core.db.q("SELECT n_state FROM nets")]
    assert states == [1, 1]


def test_ingest_rejects_malformed(core):
    report = core.add_hashlines(["not-a-hashline", "WPA*09*zz*x*y*z*a*b*c"])
    assert report["bad"] == 2 and report["new"] == 0


# -- scheduler -------------------------------------------------------------


def _released(core):
    core.db.x("UPDATE nets SET algo = '' WHERE algo IS NULL")


def test_get_work_lifecycle(core):
    lines = [
        tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="w1"),
        tfx.make_eapol_line(b"other-pass-9", ESSID, keyver=2, seed="w2"),
        tfx.make_eapol_line(b"third-pass-3", b"OtherNet", keyver=2, seed="w3"),
    ]
    core.add_hashlines(lines)
    assert core.get_work(1) is None  # nets not yet released (algo IS NULL)
    _released(core)
    assert core.get_work(1) is None  # no dicts yet
    _add_dict(core, [b"foo-password", PSK], rules=":\n$1")
    _add_dict(core, [b"a" * 9] * 3, name="bigger.txt.gz")

    work = core.get_work(1)
    assert work is not None
    # same-SSID grouping: both TestLanParty nets ship in one unit
    essids = {hl.parse(s).essid for s in work["hashes"]}
    assert essids == {ESSID}
    assert len(work["hashes"]) == 2
    assert len(work["dicts"]) == 1  # dictcount honored
    import base64
    assert base64.b64decode(work["rules"]).decode().splitlines() == [":", "$1"]

    # coverage leased under the hkey; second unit goes to the other ssid
    leased = core.db.q1("SELECT COUNT(*) c FROM n2d WHERE hkey = ?", (work["hkey"],))["c"]
    assert leased == 2
    work2 = core.get_work(5)
    assert {hl.parse(s).essid for s in work2["hashes"]} == {b"OtherNet"}
    assert len(work2["dicts"]) == 2  # both dicts still untried for this net

    # keyspace exhausted: nothing left to hand out
    work3 = core.get_work(15)
    assert work3 is not None  # TestLanParty x bigger dict remains
    assert core.get_work(15) is None


def test_put_work_verifies_and_reuses_pmk(core):
    l1 = tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="pw1")
    l2 = tfx.make_pmkid_line(PSK, ESSID, seed="pw2")  # sibling, same ssid
    core.add_hashlines([l1, l2])
    _released(core)
    _add_dict(core, [PSK])
    work = core.get_work(1)
    bssid = hl.parse(l1).mac_ap.hex()

    # bogus claim: rejected by independent re-verification
    core.put_work({"hkey": work["hkey"], "type": "bssid",
                   "cand": [{"k": bssid, "v": b"wrongpass1".hex()}]})
    assert core.db.q1("SELECT COUNT(*) c FROM nets WHERE n_state = 1")["c"] == 0

    # valid claim: accepted, and the PMK sweeps the same-ssid sibling
    core.put_work({"hkey": work["hkey"], "type": "bssid",
                   "cand": [{"k": bssid, "v": PSK.hex()}]})
    rows = core.db.q("SELECT n_state, pass, pmk FROM nets")
    assert all(r["n_state"] == 1 and r["pass"] == PSK for r in rows)
    assert all(r["pmk"] == oracle.pmk_from_psk(PSK, ESSID) for r in rows)
    # work unit closed: lease cleared
    assert core.db.q1("SELECT COUNT(*) c FROM n2d WHERE hkey IS NOT NULL")["c"] == 0


def test_put_work_broken_essid_cascade(core):
    """A sibling whose MIC verifies under the wrong-ESSID PMK is bogus
    (broken essid) and must be cascade-deleted."""
    l1 = tfx.make_pmkid_line(PSK, ESSID, seed="be1")
    core.add_hashlines([l1])
    h1 = hl.parse(l1)
    # forge a sibling: same bssid, different stored essid, but MIC computed
    # from the ESSID-derived PMK (so it "verifies" with that PMK)
    pmk = oracle.pmk_from_psk(PSK, ESSID)
    mac_sta2 = bytes.fromhex("02aabbccddef")
    pmkid2 = oracle.compute_pmkid(pmk, h1.mac_ap, mac_sta2)
    forged = hl.serialize(hl.TYPE_PMKID, pmkid2, h1.mac_ap, mac_sta2,
                          b"WrongSSID", message_pair=1)
    core.add_hashlines([forged])
    assert core.db.q1("SELECT COUNT(*) c FROM nets")["c"] == 2

    net = core.db.q1("SELECT * FROM nets WHERE ssid = ?", (ESSID,))
    core._try_accept(net, PSK)
    rows = core.db.q("SELECT ssid, n_state FROM nets")
    assert len(rows) == 1 and rows[0]["ssid"] == ESSID and rows[0]["n_state"] == 1


# -- jobs ------------------------------------------------------------------


def test_single_mode_candidates():
    cands = list(single_mode_candidates(bytes.fromhex("a0b1c2d3e4f5"), b"HomeNet"))
    assert b"a0b1c2d3e4f5" in cands
    assert b"a0b1c2d3e4f6" in cands  # bssid + 1
    assert b"HomeNet1" in cands and b"HomeNet123" in cands


def test_keygen_precompute_release_and_crack(core):
    # net crackable by the Single generator: psk = ssid + "123"
    line = tfx.make_eapol_line(b"HomeNet123", b"HomeNet", keyver=2, seed="kg")
    core.add_hashlines([line])
    stats = keygen_precompute(core)
    assert stats == {"processed": 1, "cracked": 1}
    row = core.db.q1("SELECT * FROM nets")
    assert row["n_state"] == 1 and row["pass"] == b"HomeNet123"
    assert row["algo"] == "Single"
    assert core.db.q1("SELECT COUNT(*) c FROM rkg WHERE n_state = 1")["c"] == 1

    # uncrackable net just gets released (algo = '')
    line2 = tfx.make_eapol_line(b"u$@-random-9911x", b"ZNet", keyver=2, seed="kg2")
    core.add_hashlines([line2])
    keygen_precompute(core)
    row2 = core.db.q1("SELECT algo FROM nets WHERE ssid = ?", (b"ZNet",))
    assert row2["algo"] == ""


def test_maintenance_stats_and_lease_reap(core):
    core.add_hashlines([tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="m1")])
    _released(core)
    _add_dict(core, [PSK])
    work = core.get_work(1)
    # age the lease beyond the reap window
    core.db.x("UPDATE n2d SET ts = ts - 4 * 3600 WHERE hkey = ?", (work["hkey"],))
    stats = maintenance(core)
    assert stats["nets"] == 1 and stats["uncracked"] == 1
    assert core.db.q1("SELECT COUNT(*) c FROM n2d WHERE hkey IS NOT NULL")["c"] == 0
    # coverage row STAYS (dict counted as tried) — reference semantics
    assert core.db.q1("SELECT COUNT(*) c FROM n2d")["c"] == 1


# -- WSGI API --------------------------------------------------------------


def _call(app, method="GET", path="/", qs="", body=b""):
    out = {}

    def start_response(status, headers):
        out["status"] = status

    environ = {
        "REQUEST_METHOD": method,
        "PATH_INFO": path,
        "QUERY_STRING": qs,
        "CONTENT_LENGTH": str(len(body)),
        "wsgi.input": io.BytesIO(body),
        "REMOTE_ADDR": "9.9.9.9",
    }
    chunks = app(environ, start_response)
    return out["status"], b"".join(chunks)


def test_wsgi_endpoints(core):
    app = make_wsgi_app(core)

    # old client version gated
    status, body = _call(app, "POST", qs="get_work=2.0.0")
    assert body == b"Version"
    # no nets yet
    status, body = _call(app, "POST", qs="get_work=2.2.0",
                         body=json.dumps({"dictcount": 1}).encode())
    assert body == b"No nets"

    # submit a capture over HTTP
    blob, expected = tfx.make_handshake_capture(PSK, ESSID, probes=[b"PrSsid"])
    status, body = _call(app, "POST", body=blob)
    assert json.loads(body)["new"] == expected
    _released(core)
    dhash = _add_dict(core, [b"xxxxxxxxx", PSK])

    status, body = _call(app, "POST", qs="get_work=2.2.0",
                         body=json.dumps({"dictcount": 1}).encode())
    work = json.loads(body)
    assert work["dicts"][0]["dhash"] == dhash
    assert work.get("prdict") is True

    # dict download + md5
    status, body = _call(app, path="/" + work["dicts"][0]["dpath"])
    assert hashlib.md5(body).hexdigest() == dhash

    # prdict stream
    status, body = _call(app, qs="prdict=" + work["hkey"])
    assert b"PrSsid" in gzip.decompress(body)

    # put_work round trip
    bssid = hl.parse(work["hashes"][0]).mac_ap.hex()
    status, body = _call(app, "POST", qs="put_work", body=json.dumps({
        "hkey": work["hkey"], "type": "bssid",
        "cand": [{"k": bssid, "v": PSK.hex()}],
    }).encode())
    assert body == b"OK"
    assert core.db.q1("SELECT COUNT(*) c FROM nets WHERE n_state = 1")["c"] >= 1

    # stats endpoint
    maintenance(core)
    status, body = _call(app, qs="stats")
    assert json.loads(body)["cracked"] >= 1


def _parse_prometheus(text: str) -> dict:
    """{(name, frozenset(labels)): value} plus {"#types": {name: type}}
    — a strict little v0.0.4 parser: every non-comment line must be
    ``name[{labels}] value``."""
    samples, types = {}, {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ", 3)
            types[name] = mtype
            continue
        if line.startswith("#"):
            continue
        metric, _, value = line.rpartition(" ")
        labels = frozenset()
        if "{" in metric:
            name, _, rest = metric.partition("{")
            body = rest.rstrip("}")
            labels = frozenset(
                (kv.split("=", 1)[0], kv.split("=", 1)[1].strip('"'))
                for kv in body.split(","))
        else:
            name = metric
        samples[(name, labels)] = float(value)
    return {"samples": samples, "types": types}


def test_metrics_endpoint_prometheus_scrape(tmp_path):
    """?metrics serves parseable Prometheus text-format v0.0.4 with
    per-endpoint request counters + latency histograms, scheduler
    counters, and the scrape-time lease gauges (ISSUE-2 acceptance)."""
    from dwpa_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    core = ServerCore(Database(":memory:"), dictdir=str(tmp_path / "dicts"),
                      capdir=str(tmp_path / "caps"), registry=reg)
    app = make_wsgi_app(core)

    core.add_hashlines([tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="mx")])
    _released(core)
    _add_dict(core, [PSK])
    status, body = _call(app, "POST", qs="get_work=2.2.0",
                         body=json.dumps({"dictcount": 1}).encode())
    work = json.loads(body)
    bssid = hl.parse(work["hashes"][0]).mac_ap.hex()
    _call(app, "POST", qs="put_work", body=json.dumps({
        "hkey": work["hkey"], "type": "bssid",
        "cand": [{"k": bssid, "v": PSK.hex()}]}).encode())
    maintenance(core)

    # batched pre-crack job over one fresh net (Single cracks it)
    core.add_hashlines([tfx.make_eapol_line(b"metricsnet1", b"MetricsNet",
                                            keyver=2, seed="mx2")])
    from dwpa_tpu.server.jobs import precrack
    assert precrack(core, device="off")["cracked"] == 1

    status, body = _call(app, qs="metrics")
    assert status.startswith("200")
    prom = _parse_prometheus(body.decode())
    s = prom["samples"]
    assert prom["types"]["dwpa_http_requests_total"] == "counter"
    assert prom["types"]["dwpa_http_request_seconds"] == "histogram"
    assert s[("dwpa_http_requests_total",
              frozenset({("endpoint", "get_work"), ("status", "200")}))] == 1
    assert s[("dwpa_http_requests_total",
              frozenset({("endpoint", "put_work"), ("status", "200")}))] == 1
    # per-endpoint latency histogram: +Inf bucket == count, sum present
    inf = s[("dwpa_http_request_seconds_bucket",
             frozenset({("endpoint", "get_work"), ("le", "+Inf")}))]
    cnt = s[("dwpa_http_request_seconds_count",
             frozenset({("endpoint", "get_work")}))]
    assert inf == cnt == 1
    assert ("dwpa_http_request_seconds_sum",
            frozenset({("endpoint", "get_work")})) in s
    # scheduler + claim counters from core.py
    assert s[("dwpa_server_work_issued_total", frozenset())] == 1
    assert s[("dwpa_server_claims_total",
              frozenset({("verdict", "accepted")}))] == 1
    # scrape-time lease/net gauges (the unit was accepted: lease closed)
    assert s[("dwpa_server_leases_active", frozenset())] == 0
    # both the volunteer claim and the pre-crack found are cracked nets
    assert s[("dwpa_server_nets", frozenset({("state", "cracked")}))] == 2
    # maintenance-job duration rode the span histogram
    assert s[("dwpa_span_seconds_count",
              frozenset({("span", "job:maintenance")}))] == 1
    # pre-crack sweep: per-source candidate counters, the free-found
    # counter, the batch fill gauge and the job span all on one scrape
    assert prom["types"]["dwpa_precrack_candidates_total"] == "counter"
    assert s[("dwpa_precrack_candidates_total",
              frozenset({("source", "single")}))] >= 1
    assert s[("dwpa_precrack_free_founds_total", frozenset())] == 1
    assert ("dwpa_precrack_batch_fill_fraction", frozenset()) in s
    assert s[("dwpa_span_seconds_count",
              frozenset({("span", "job:precrack")}))] == 1

    # the JSON wire form parses and agrees on the counter
    status, body = _call(app, qs="metrics=json")
    snap = json.loads(body)
    reqs = snap["dwpa_http_requests_total"]["samples"]
    got = {tuple(sorted(x["labels"].items())): x["value"] for x in reqs}
    assert got[(("endpoint", "get_work"), ("status", "200"))] == 1
    # scrapes count themselves (this is the second one)
    status, body = _call(app, qs="metrics")
    prom2 = _parse_prometheus(body.decode())
    assert prom2["samples"][(
        "dwpa_http_requests_total",
        frozenset({("endpoint", "metrics"), ("status", "200")}))] == 2


def test_put_work_hash_type_raw_digit_psk(core):
    """'hash' claims carry raw-text PSKs: an all-digit key (valid hex!)
    must not be hex-decoded (ADVICE r1; common.php:890-898)."""
    digit_psk = b"12345678"
    line = tfx.make_pmkid_line(digit_psk, ESSID, seed="hash-claim")
    core.add_hashlines([line])
    nhash = core.db.q1("SELECT hash FROM nets")["hash"]
    core.put_work({"type": "hash",
                   "cand": [{"k": nhash.hex(), "v": digit_psk.decode()}]})
    row = core.db.q1("SELECT n_state, pass FROM nets")
    assert row["n_state"] == 1 and row["pass"] == digit_psk


def test_put_work_hash_type_hex_notation(core):
    """'hash' claims may use hashcat $HEX[...] notation for binary PSKs."""
    psk = b"caf\xc3\xa9pass"  # 'café' in utf-8 + suffix
    line = tfx.make_pmkid_line(psk, ESSID, seed="hex-claim")
    core.add_hashlines([line])
    nhash = core.db.q1("SELECT hash FROM nets")["hash"]
    core.put_work({"type": "hash",
                   "cand": [{"k": nhash.hex(), "v": "$HEX[%s]" % psk.hex()}]})
    assert core.db.q1("SELECT n_state FROM nets")["n_state"] == 1


def test_put_work_ssid_type_hex_key(core):
    """ssid claims: key is the hex-encoded ESSID, value a hex PSK
    (common.php:886-887)."""
    line = tfx.make_pmkid_line(PSK, ESSID, seed="ssid-claim")
    core.add_hashlines([line])
    core.put_work({"type": "ssid",
                   "cand": [{"k": ESSID.hex(), "v": PSK.hex()}]})
    assert core.db.q1("SELECT n_state FROM nets")["n_state"] == 1


def test_wsgi_oversized_body_rejected_413(core):
    """Oversized uploads are rejected outright, never truncated+ingested."""
    app = make_wsgi_app(core)
    out = {}

    def start_response(status, headers):
        out["status"] = status

    environ = {
        "REQUEST_METHOD": "POST",
        "PATH_INFO": "/",
        "QUERY_STRING": "",
        "CONTENT_LENGTH": str(65 * 1024 * 1024),
        "wsgi.input": io.BytesIO(b"x"),
        "REMOTE_ADDR": "9.9.9.9",
    }
    b"".join(app(environ, start_response))
    assert out["status"].startswith("413")
    assert core.db.q1("SELECT COUNT(*) c FROM submissions")["c"] == 0


def test_regen_cracked_dict_deterministic(core, tmp_path):
    """Identical content -> identical gzip bytes (mtime=0), so dhash and
    client caches only churn when the word list changes."""
    from dwpa_tpu.server.jobs import regen_cracked_dict

    line = tfx.make_pmkid_line(PSK, ESSID, seed="regen")
    core.add_hashlines([line])
    nhash = core.db.q1("SELECT hash FROM nets")["hash"]
    core.put_work({"type": "hash", "cand": [{"k": nhash.hex(), "v": PSK.decode()}]})
    path = str(tmp_path / "cracked.txt.gz")
    regen_cracked_dict(core, path)
    first = open(path, "rb").read()
    regen_cracked_dict(core, path)
    assert open(path, "rb").read() == first


def test_eapol_descriptor_type_gate():
    """802.1X type-3 frames with a non-RSN/WPA descriptor type must not be
    parsed as handshake messages."""
    from dwpa_tpu.server.capture import _parse_eapol_key

    # craft a bogus EAPOL-Key frame: correct shape, descriptor type 1
    import struct as _s
    body = bytearray(99)
    body[1] = 3  # 802.1X packet type: EAPOL-Key
    body[4] = 1  # descriptor type: RC4 (not 2/254)
    _s.pack_into(">H", body, 5, 0x010A)  # pairwise|mic
    assert _parse_eapol_key(b"\xaa" * 6, b"\xbb" * 6, bytes(body)) is None
    body[4] = 2
    assert _parse_eapol_key(b"\xaa" * 6, b"\xbb" * 6, bytes(body)) is not None


def test_concurrent_get_work_never_double_issues():
    """N threads hammering get_work: every unit gets a distinct hkey and
    no (net, dict) lease is issued twice — the get_work.php:49 SHM-mutex
    semantics under the threaded server."""
    import threading

    core = ServerCore(Database(":memory:"))
    for i in range(6):
        core.add_hashlines(
            [tfx.make_pmkid_line(b"ccpass%03d" % i, b"CcNet%d" % i,
                                 seed=f"cc{i}")])
    core.db.x("UPDATE nets SET algo = ''")
    for i in range(6):
        core.add_dict(f"dict/cc{i}.txt.gz", f"cc{i}", "0" * 32, 10 + i)

    works, errs = [], []

    def worker():
        try:
            for _ in range(4):
                w = core.get_work(2)
                if w:
                    works.append(w)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    hkeys = [w["hkey"] for w in works]
    assert len(hkeys) == len(set(hkeys))  # unique unit ids
    # The real double-issue symptom: a unit whose OR-IGNOREd lease rows
    # were clobbered by a racing unit — its hkey then owns fewer rows
    # than hashes x dicts.  Every returned unit must own exactly its
    # claimed coverage, and the global row count must add up.
    total = 0
    for w in works:
        expect = len(w["hashes"]) * len(w["dicts"])
        owned = core.db.q1(
            "SELECT COUNT(*) c FROM n2d WHERE hkey = ?", (w["hkey"],)
        )["c"]
        assert owned == expect, (w["hkey"], owned, expect)
        total += expect
    assert core.db.q1("SELECT COUNT(*) c FROM n2d")["c"] == total


# -- browser multipart upload + capture caps + dated archive (round 3) -----


def _multipart_body(files, fields=None, boundary="----WebKitFormBoundaryx7Qq"):
    """A browser-shaped multipart/form-data body (CRLF line ends,
    Content-Type on file parts), as Chrome/Firefox emit for the
    ?submit form (ui.page_submit)."""
    out = bytearray()
    for name, value in (fields or {}).items():
        out += (f"--{boundary}\r\nContent-Disposition: form-data; "
                f'name="{name}"\r\n\r\n{value}\r\n').encode()
    for name, (fname, blob) in files.items():
        out += (f"--{boundary}\r\nContent-Disposition: form-data; "
                f'name="{name}"; filename="{fname}"\r\n'
                "Content-Type: application/octet-stream\r\n\r\n").encode()
        out += blob + b"\r\n"
    out += f"--{boundary}--\r\n".encode()
    return bytes(out), f"multipart/form-data; boundary={boundary}"


def _call_ct(app, body, ctype, qs=""):
    out = {}

    def start_response(status, headers):
        out["status"] = status

    environ = {
        "REQUEST_METHOD": "POST",
        "PATH_INFO": "/",
        "QUERY_STRING": qs,
        "CONTENT_TYPE": ctype,
        "CONTENT_LENGTH": str(len(body)),
        "wsgi.input": io.BytesIO(body),
        "REMOTE_ADDR": "9.9.9.9",
    }
    return out, b"".join(app(environ, start_response))


def test_browser_multipart_upload_ingests_and_cracks(core):
    """The ?submit form posts multipart/form-data back to /?submit
    (submit.php:18-31 handles $_FILES on the same URL); the capture
    must ingest through the same pipeline as the raw path and the
    resulting net must crack end-to-end."""
    app = make_wsgi_app(core)
    blob, expected = tfx.make_handshake_capture(PSK, ESSID)
    body, ctype = _multipart_body({"file": ("station.pcap", blob)})
    # the exact target a browser derives from the action-less form
    out, resp = _call_ct(app, body, ctype, qs="submit")
    assert out["status"].startswith("200")
    assert json.loads(resp)["new"] == expected
    # the ingested nets crack with the real PSK via the normal accept path
    _released(core)
    _add_dict(core, [b"not-the-one", PSK])
    _, wbody = _call(app, "POST", qs="get_work=2.2.0",
                     body=json.dumps({"dictcount": 1}).encode())
    work = json.loads(wbody)
    bssid = hl.parse(work["hashes"][0]).mac_ap.hex()
    _, pbody = _call(app, "POST", qs="put_work", body=json.dumps({
        "hkey": work["hkey"], "type": "bssid",
        "cand": [{"k": bssid, "v": PSK.hex()}],
    }).encode())
    assert pbody == b"OK"
    assert core.db.q1("SELECT COUNT(*) c FROM nets WHERE n_state=1")["c"] >= 1


def test_multipart_binary_safe_and_missing_file(core):
    """Binary capture bytes containing CRLF/dash runs survive the part
    split; a multipart body without any file part is a 400."""
    from dwpa_tpu.server.api import _parse_multipart

    blob = b"\r\n--tricky\r\n" + bytes(range(256)) * 4
    body, ctype = _multipart_body({"file": ("x.bin", blob)},
                                  fields={"key": "a" * 32})
    fields, files = _parse_multipart(body, ctype)
    assert files["file"] == ("x.bin", blob)
    assert fields["key"] == "a" * 32

    app = make_wsgi_app(core)
    body, ctype = _multipart_body({}, fields={"note": "no file here"})
    out, resp = _call_ct(app, body, ctype)
    assert out["status"].startswith("400")


def test_capture_cap_is_tight_8mib(core):
    """Captures get their own 8 MiB cap (the reference's PHP upload
    posture is single-digit MiB): cap+1 is 413 before any read; a
    same-size claim under the cap proceeds to parsing (400 garbage)."""
    from dwpa_tpu.server.api import CAPTURE_BODY_CAP

    app = make_wsgi_app(core)
    out = {}

    def start_response(status, headers):
        out["status"] = status

    def env(n):
        return {
            "REQUEST_METHOD": "POST", "PATH_INFO": "/", "QUERY_STRING": "",
            "CONTENT_LENGTH": str(n), "wsgi.input": io.BytesIO(b"not-a-cap"),
            "REMOTE_ADDR": "9.9.9.9",
        }

    b"".join(app(env(CAPTURE_BODY_CAP + 1), start_response))
    assert out["status"].startswith("413")
    assert core.db.q1("SELECT COUNT(*) c FROM submissions")["c"] == 0
    b"".join(app(env(CAPTURE_BODY_CAP), start_response))
    assert out["status"].startswith("400")  # read, parsed, rejected as garbage


def test_dated_capture_archive_and_reorder(core, tmp_path):
    """Uploads archive under capdir/Y/m/d (common.php:492-514); the
    reorder-captures tool migrates flat legacy files by mtime."""
    import os
    import time as _t

    from dwpa_tpu.server.tools import reorder_captures

    blob, _ = tfx.make_handshake_capture(PSK, ESSID)
    submit_capture(core, blob)
    row = core.db.q1("SELECT localfile FROM submissions")
    day = _t.strftime("%Y/%m/%d")
    assert f"/{day}/" in row["localfile"].replace("\\", "/")
    assert os.path.isfile(row["localfile"])

    # legacy flat file: plant one + a matching DB row, then reorder
    legacy_md5 = hashlib.md5(b"legacy-blob").hexdigest()
    flat = os.path.join(core.capdir, legacy_md5)
    with open(flat, "wb") as f:
        f.write(b"legacy-blob")
    old = _t.time() - 400 * 86400
    os.utime(flat, (old, old))
    core.db.x("INSERT INTO submissions(localfile, hash, ip) VALUES (?,?,?)",
              (flat, hashlib.md5(b"legacy-blob").digest(), ""))
    rep = reorder_captures(core)
    assert rep == {"moved": 1, "db_updated": 1}
    newpath = core.db.q1(
        "SELECT localfile FROM submissions WHERE hash = ?",
        (hashlib.md5(b"legacy-blob").digest(),))["localfile"]
    expect_day = _t.strftime("%Y/%m/%d", _t.localtime(old))
    assert f"/{expect_day}/" in newpath.replace("\\", "/")
    assert os.path.isfile(newpath)
    assert reorder_captures(core) == {"moved": 0, "db_updated": 0}  # idempotent


def test_sched_lock_is_cross_process(tmp_path):
    """The scheduler mutex must serialize across processes (the
    reference's SHM lockfile, common.php:320-332): serve and jobs run
    as separate processes in the documented deployment."""
    import subprocess
    import sys
    import time as _t

    from dwpa_tpu.server.core import _SchedLock

    dbpath = str(tmp_path / "wpa.sqlite")
    child = subprocess.Popen(
        [sys.executable, "-c", (
            "import fcntl, os, sys, time\n"
            f"fd = os.open({dbpath + '.getwork.lock'!r}, os.O_CREAT | os.O_RDWR)\n"
            "fcntl.flock(fd, fcntl.LOCK_EX)\n"
            "print('locked', flush=True)\n"
            "time.sleep(1.0)\n"
            "fcntl.flock(fd, fcntl.LOCK_UN)\n"
        )],
        stdout=subprocess.PIPE,
    )
    try:
        assert child.stdout.readline().strip() == b"locked"
        lock = _SchedLock(dbpath)
        t0 = _t.perf_counter()
        with lock:
            waited = _t.perf_counter() - t0
        # the parent must have blocked until the child released (~1 s)
        assert waited > 0.4, waited
        # reentrancy still holds
        with lock:
            with lock:
                pass
    finally:
        child.wait()


def test_router_fuzz_never_crashes(core):
    """Seeded fuzz over the whole WSGI surface: random methods, paths,
    query keys, bodies, content types, cookies — every response must be
    a handled status (2xx/3xx/4xx), never a 5xx or an unhandled
    exception.  The front controller's only allowed failure modes are
    400 (bad input) and 413 (oversize), like the reference's guarded
    index.php routing."""
    import random

    app = make_wsgi_app(core)
    rng = random.Random(7)
    keys = ["get_work", "put_work", "prdict", "api", "stats", "nets",
            "search", "my_nets", "dicts", "home", "submit", "get_key",
            "key", "page", "remkey"]
    bodies = [b"", b"\x00" * 64, b"{bad json", b"a=b&c=d", b"WPA*junk",
              b"--x\r\nContent-Disposition: form-data\r\n\r\n",
              bytes(range(256)), b"mail=x&key=" + b"f" * 32]
    ctypes = ["", "application/x-www-form-urlencoded", "application/json",
              "multipart/form-data; boundary=x", "multipart/form-data",
              "text/plain"]
    paths = ["/", "", "/dict/../etc/passwd", "/dict/x.gz", "/hc/../../x",
             "/zzz"]
    vals = ["", "1", "ff" * 16, "%00", "x" * 200, "2.2.0"]
    for _ in range(1500):
        qs = "&".join(f"{rng.choice(keys)}={rng.choice(vals)}"
                      for _ in range(rng.randrange(0, 4)))
        body = rng.choice(bodies)
        environ = {
            "REQUEST_METHOD": rng.choice(["GET", "POST", "PUT", "HEAD"]),
            "PATH_INFO": rng.choice(paths),
            "QUERY_STRING": qs,
            "CONTENT_TYPE": rng.choice(ctypes),
            "CONTENT_LENGTH": rng.choice([str(len(body)), "", "-5", "zz",
                                          "999"]),
            "wsgi.input": io.BytesIO(body),
            "REMOTE_ADDR": "1.2.3.4",
            "HTTP_COOKIE": rng.choice(["", "key=zz", "key=" + "a" * 32,
                                       ";;;="]),
            "HTTP_ACCEPT": rng.choice(["", "text/html"]),
        }
        status = []
        list(app(environ, lambda s, h: status.append(s)))
        assert status and not status[0].startswith("5"), (environ, status)


def test_gzip_bomb_rejected_413(core):
    """A small gzip body that inflates past the capture cap is rejected
    before the decompressed blob can exhaust memory (the cap applies to
    the decompressed size, not just the wire size)."""
    from dwpa_tpu.server.api import CAPTURE_BODY_CAP

    bomb = gzip.compress(b"\x00" * (CAPTURE_BODY_CAP + 1024), 9)
    assert len(bomb) < 1024 * 1024  # tiny on the wire
    app = make_wsgi_app(core)
    out = {}
    environ = {
        "REQUEST_METHOD": "POST", "PATH_INFO": "/", "QUERY_STRING": "",
        "CONTENT_LENGTH": str(len(bomb)), "wsgi.input": io.BytesIO(bomb),
        "REMOTE_ADDR": "9.9.9.9",
    }
    b"".join(app(environ, lambda s, h: out.update(status=s)))
    assert out["status"].startswith("413")
    assert core.db.q1("SELECT COUNT(*) c FROM submissions")["c"] == 0
    # a normal gzipped capture still ingests
    blob, expected = tfx.make_handshake_capture(PSK, ESSID)
    report = submit_capture(core, gzip.compress(blob))
    assert report["new"] == expected


def test_api_waits_out_external_writer(tmp_path):
    """An external connection holding a write transaction (ops tooling,
    a manual sqlite session) must make API writes WAIT, not 500: the
    reference's MySQL posture tolerates concurrent writers, so the
    sqlite layer carries a 30 s busy timeout (found by a soak run where
    a setup script's open transaction 500'd an upload)."""
    import sqlite3
    import threading

    db = Database(str(tmp_path / "w.db"))
    # the discriminating check: sqlite's built-in default is 5 s, which
    # the soak's multi-second transactions exceeded; pin the raised value
    assert db.conn.execute("PRAGMA busy_timeout").fetchone()[0] == 30000
    core = ServerCore(db, dictdir=str(tmp_path / "d"),
                      capdir=str(tmp_path / "c"))
    app = make_wsgi_app(core)
    ext = sqlite3.connect(str(tmp_path / "w.db"), check_same_thread=False)
    ext.execute("BEGIN IMMEDIATE")  # hold the write lock

    def release():
        ext.commit()
        ext.close()

    t = threading.Timer(1.0, release)
    t.start()
    blob, expected = tfx.make_handshake_capture(PSK, ESSID)
    out = {}
    environ = {
        "REQUEST_METHOD": "POST", "PATH_INFO": "/", "QUERY_STRING": "",
        "CONTENT_LENGTH": str(len(blob)), "wsgi.input": io.BytesIO(blob),
        "REMOTE_ADDR": "9.9.9.9",
    }
    resp = b"".join(app(environ, lambda s, h: out.update(status=s)))
    t.join()
    assert out["status"].startswith("200"), (out, resp)
    assert json.loads(resp)["new"] == expected


# -- epoch leases, admission control, crash-safe scheduler (round 4) -------


def _call_hdrs(app, method="GET", path="/", qs="", body=b""):
    """Like _call but also returns the response headers as a dict."""
    out = {}

    def start_response(status, headers):
        out["status"] = status
        out["headers"] = dict(headers)

    environ = {
        "REQUEST_METHOD": method,
        "PATH_INFO": path,
        "QUERY_STRING": qs,
        "CONTENT_LENGTH": str(len(body)),
        "wsgi.input": io.BytesIO(body),
        "REMOTE_ADDR": "9.9.9.9",
    }
    chunks = app(environ, start_response)
    return out["status"], out["headers"], b"".join(chunks)


def _released_core(nets=2, dicts=2):
    """A ServerCore with `nets` released nets and `dicts` dicts."""
    core = ServerCore(Database(":memory:"))
    for i in range(nets):
        core.add_hashlines(
            [tfx.make_pmkid_line(b"lease%03d" % i, b"LeaseNet%d" % i,
                                 seed=f"ls{i}")])
    core.db.x("UPDATE nets SET algo = ''")
    for i in range(dicts):
        core.add_dict(f"dict/ls{i}.txt.gz", f"ls{i}", "0" * 32, 10 + i)
    return core


def test_dictcount_non_numeric_is_clean_400(core):
    """Regression: a non-numeric dictcount (string garbage, or a
    container — int() raises TypeError on those, which the generic
    ValueError net never caught) must 400, not traceback to a 500."""
    app = make_wsgi_app(core)
    for bad in ("lots", [3], {"n": 3}, None):
        body = json.dumps({"dictcount": bad}).encode()
        status, resp = _call(app, "POST", qs="get_work=2.2.0", body=body)
        assert status.startswith("400"), (bad, status, resp)
        assert resp == b"bad dictcount"
    # numeric strings still coerce (reference accepts "2")
    status, resp = _call(app, "POST", qs="get_work=2.2.0",
                         body=json.dumps({"dictcount": "2"}).encode())
    assert not status.startswith("400"), (status, resp)


def test_admission_control_429_retry_after():
    """Beyond max_inflight live leases, get_work answers 429 with a
    Retry-After header; a lease release reopens admission."""
    core = _released_core(nets=3, dicts=2)
    core.max_inflight = 1
    app = make_wsgi_app(core)
    body = json.dumps({"dictcount": 1}).encode()

    status, _, resp = _call_hdrs(app, "POST", qs="get_work=2.2.0", body=body)
    assert status.startswith("200")
    work = json.loads(resp)

    status, headers, resp = _call_hdrs(app, "POST", qs="get_work=2.2.0",
                                       body=body)
    assert status.startswith("429"), (status, resp)
    assert float(headers["Retry-After"]) >= 1
    assert core.registry is None or True  # overload counter is optional obs

    # releasing the lease (an empty submission still releases) reopens
    status, _, resp = _call_hdrs(
        app, "POST", qs="put_work",
        body=json.dumps({"hkey": work["hkey"], "epoch": work["epoch"],
                         "cand": []}).encode())
    assert resp == b"OK"
    status, _, _ = _call_hdrs(app, "POST", qs="get_work=2.2.0", body=body)
    assert status.startswith("200")


def test_lease_epoch_blocks_stale_holder():
    """A reaped-then-reissued unit cannot be released (or double-
    credited) by the original holder: the release is keyed by epoch."""
    from dwpa_tpu.server.jobs import maintenance

    core = _released_core(nets=1, dicts=2)
    w1 = core.get_work(1)  # 1 of 2 dicts: a reissue has an untried dict
    assert w1 is not None
    # the holder goes dark: backdate past LEASE_REAP_S (3 h), reap
    core.db.x("UPDATE n2d SET ts = ts - 14400")
    core.db.x("UPDATE leases SET issued = issued - 14400")
    maintenance(core)
    lease1 = core.db.q1("SELECT state FROM leases WHERE hkey = ?",
                        (w1["hkey"],))
    assert lease1["state"] == 2  # reaped

    w2 = core.get_work(1)  # reissued to a new holder
    assert w2 is not None and w2["epoch"] > w1["epoch"]

    # stale holder's release: matches nothing, w2's lease stays live
    assert core.put_work({"hkey": w1["hkey"], "epoch": w1["epoch"],
                          "cand": []}) is True
    live = core.db.q1(
        "SELECT COUNT(*) c FROM leases WHERE hkey = ? AND state = 0",
        (w2["hkey"],))["c"]
    assert live == 1
    # new holder's release lands; a duplicate submit is idempotent
    core.put_work({"hkey": w2["hkey"], "epoch": w2["epoch"], "cand": []})
    core.put_work({"hkey": w2["hkey"], "epoch": w2["epoch"], "cand": []})
    assert core.db.q1(
        "SELECT COUNT(*) c FROM leases WHERE state = 0")["c"] == 0
    assert core.db.q1(
        "SELECT COUNT(*) c FROM leases WHERE hkey = ? AND state = 1",
        (w2["hkey"],))["c"] == 1


def test_get_work_storm_epoch_leases():
    """N threads issuing and releasing concurrently: every coverage row
    belongs to at most one hkey, every live lease is unique, and the
    ledger passes the chaos invariant sweep afterwards."""
    import threading

    from dwpa_tpu.chaos import sweep_invariants

    core = _released_core(nets=6, dicts=4)
    works, errs = [], []
    lock = threading.Lock()

    def worker():
        try:
            for _ in range(6):
                w = core.get_work(1)
                if w is None:
                    continue
                with lock:
                    works.append(w)
                core.put_work({"hkey": w["hkey"], "epoch": w["epoch"],
                               "cand": []})
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    hkeys = [w["hkey"] for w in works]
    assert len(hkeys) == len(set(hkeys))
    # one lease row per issued unit, none live (all released), and the
    # double-live / orphan-coverage sweep comes back clean
    assert core.db.q1("SELECT COUNT(*) c FROM leases")["c"] == len(works)
    assert core.db.q1(
        "SELECT COUNT(*) c FROM leases WHERE state = 0")["c"] == 0
    assert sweep_invariants(core.db) == []


def test_restart_mid_unit_clean_lease(tmp_path):
    """Server restart between issue and submit: the reopened core sees
    the lease cleanly outstanding (submit lands, exactly once) — and a
    reopened core after a reap sees it cleanly reaped (stale submit
    credits nothing).  Never half of either."""
    from dwpa_tpu.chaos import sweep_invariants

    dbpath = str(tmp_path / "wpa.sqlite")
    core = ServerCore(Database(dbpath))
    core.add_hashlines(
        [tfx.make_pmkid_line(b"restart-psk", b"RestartNet", seed="rs0")])
    core.db.x("UPDATE nets SET algo = ''")
    core.add_dict("dict/rs.txt.gz", "rs", "0" * 32, 10)
    work = core.get_work(1)
    assert work is not None
    core.db.conn.close()

    # --- restart: brand-new Database handle over the same file
    core2 = ServerCore(Database(dbpath))
    assert sweep_invariants(core2.db) == []
    row = core2.db.q1("SELECT state, epoch FROM leases WHERE hkey = ?",
                      (work["hkey"],))
    assert row is not None and row["state"] == 0  # cleanly outstanding
    leased = core2.db.q1(
        "SELECT COUNT(*) c FROM n2d WHERE hkey = ?", (work["hkey"],))["c"]
    assert leased == 1
    assert core2.put_work({"hkey": work["hkey"], "epoch": work["epoch"],
                           "cand": []}) is True
    assert core2.db.q1("SELECT state FROM leases WHERE hkey = ?",
                       (work["hkey"],))["state"] == 1
    # the tried row survives as coverage (hkey cleared, not deleted)
    assert core2.db.q1("SELECT COUNT(*) c FROM n2d")["c"] == 1
    assert core2.db.q1(
        "SELECT COUNT(*) c FROM n2d WHERE hkey IS NOT NULL")["c"] == 0
    assert sweep_invariants(core2.db) == []
