"""Native candidate packer (native/pack_fast.cpp) — differential vs the
Python pipeline (oracle.hc_unhex + length filter + pack_passwords_be),
plus engine integration."""

import numpy as np
import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.models import m22000 as m
from dwpa_tpu.native import load_pack, pack_candidates_fast
from dwpa_tpu.oracle.m22000 import hc_unhex
from dwpa_tpu.utils import bytesops as bo

pytestmark = pytest.mark.skipif(
    load_pack() is None, reason="native pack library unavailable"
)


def _python_pipeline(words):
    pws = [hc_unhex(w) for w in words]
    return [p for p in pws if 8 <= len(p) <= 63]


CASES = [
    [b"password01", b"short", b"okaypass9"],
    [b"$HEX[41414141415a5a5a]", b"$HEX[zzzz]pad", b"$HEX[61]"],
    [b"x" * 63, b"x" * 64, b"y" * 8, b"z" * 7],
    [b"$HEX[" + b"61" * 63 + b"]", b"$HEX[" + b"62" * 64 + b"]"],
    [bytes(range(8, 40)), b"emb\x00edded0", b"nl\nin\nword"],
    [b"$HEX[4141414141414141"],  # unterminated wrapper: literal
    [],
    [b"", b"\r\n", b"1234567"],  # nothing valid
]


@pytest.mark.parametrize("words", CASES)
def test_differential_vs_python(words):
    exp = _python_pipeline(words)
    out, lens, n = pack_candidates_fast(words, 8, 63)
    assert n == len(exp)
    for i, w in enumerate(exp):
        assert bo.words_to_bytes_be(out[i])[: lens[i]] == w
        np.testing.assert_array_equal(out[i], bo.pack_passwords_be([w])[0])
    assert not out[n:].any()  # capacity rows stay zero


def test_capacity_padding():
    out, lens, n = pack_candidates_fast([b"password1"], 8, 63, capacity=32)
    assert out.shape == (32, 16) and n == 1
    assert not out[1:].any()


def test_engine_uses_fast_path_same_founds():
    """The engine cracks identically through the native prepare path
    (plain bytes list) and the Python fallback (str candidates force
    it), $HEX decode included."""
    psk = b"A" * 5 + b"\xc3\xa9abc"  # non-ascii: arrives as $HEX from wires
    line = tfx.make_pmkid_line(psk, b"PackNet", seed="np1")
    words = [b"chaff-%04d" % i for i in range(63)]
    hexed = b"$HEX[" + psk.hex().encode() + b"]"

    eng_fast = m.M22000Engine([line], batch_size=64)
    f_fast = eng_fast.crack_batch(words + [hexed])

    eng_slow = m.M22000Engine([line], batch_size=64)
    f_slow = eng_slow.crack_batch([w.decode("latin1") for w in words]
                                  + [hexed.decode("latin1")])
    assert [f.psk for f in f_fast] == [psk]
    assert [(f.psk, f.pmk) for f in f_fast] == [(f.psk, f.pmk) for f in f_slow]


def test_hc_unhex_strict_xdigit_matches_reference():
    """Whitespace in the $HEX payload is literal, not decoded — PHP
    ctype_xdigit semantics (web/common.php:3-25); native and Python
    paths must agree."""
    w = b"$HEX[61 62 63 64 65 66 67 68]"
    assert hc_unhex(w) == w  # literal, 29 bytes
    out, lens, n = pack_candidates_fast([w], 8, 63)
    assert n == 1 and lens[0] == len(w)
    assert hc_unhex(b"$HEX[]") == b""


def test_oversize_invalid_heavy_batch_keeps_shape():
    """Shape parity with the fallback: invalid words must not inflate
    the device batch."""
    eng = m.M22000Engine(
        [tfx.make_pmkid_line(b"password1", b"ShapeNet", seed="sh1")],
        batch_size=8,
    )
    words = [b"ok-word%03d" % i for i in range(10)] + [b"bad"] * 30
    prep = eng._prepare(words)
    pws, nvalid, pw_words = prep
    assert nvalid == 10
    assert pw_words.shape[0] == 16  # ceil(10/8)*8, not 40
