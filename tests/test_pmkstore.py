"""dwpa_tpu.pmkstore: the persistent cross-unit PBKDF2 cache.

Three layers under test:

- the STORE (record/frame format, reopen persistence, torn-tail
  crash-safety via fault injection, segment rotation + eviction under
  the size cap, hit/miss telemetry);
- the SPLIT STAGE (bounded static miss widths, per-ESSID hit/miss
  partitioning, the multi-host framed-slice sharding property);
- the ENGINE mixed-block path — differential against the pure-Python
  oracle PMKs (hashlib PBKDF2 is the oracle's kernel) on the same
  candidate stream: all-hit, all-miss, interleaved and
  resume-skip-across-cached-blocks, plus the recompile-sentinel proof
  that the width bucketing keeps XLA compiles bounded.
"""

import hashlib
import os

import numpy as np
import pytest

from dwpa_tpu import testing as synth
from dwpa_tpu.feed import CandidateFeed
from dwpa_tpu.feed.framing import frame_blocks
from dwpa_tpu.models.m22000 import M22000Engine
from dwpa_tpu.obs import MetricsRegistry
from dwpa_tpu.pmkstore import (PMKStore, miss_width, miss_widths, split_block,
                               word_digest)

ESSID = b"StoreNet"


def _pmk(word, essid=ESSID):
    """The oracle's PBKDF2 (oracle/m22000.check_key_m22000 computes PMKs
    with exactly this hashlib call) — the parity reference."""
    return hashlib.pbkdf2_hmac("sha1", word, essid, 4096, 32)


def _seed(store, words, essid=ESSID):
    store.put(essid, words, [_pmk(w, essid) for w in words])


def _crack(engine, words, registry=None, skip=0, on_batch=None):
    feed = CandidateFeed(iter(words), batch_size=engine.batch_size,
                         producers=1, skip=skip,
                         prepack=engine.host_packer(),
                         registry=registry or MetricsRegistry())
    try:
        return engine.crack_blocks(feed, on_batch=on_batch)
    finally:
        feed.close()


# ---------------------------------------------------------------------------
# store: record format, persistence, crash-safety, eviction
# ---------------------------------------------------------------------------


def test_put_lookup_roundtrip(tmp_path):
    store = PMKStore(str(tmp_path))
    words = [b"roundtrip-%03d" % i for i in range(10)]
    _seed(store, words)
    got = store.lookup(ESSID, words + [b"never-stored"])
    assert got[:-1] == [_pmk(w) for w in words]
    assert got[-1] is None
    # per-ESSID by construction: the same words under another ESSID miss
    assert store.lookup(b"OtherNet", words) == [None] * len(words)


def test_matrix_put_matches_bytes_put(tmp_path):
    """The engine writes back the device layout (uint32[8, m] columns);
    it must round-trip identically to explicit 32-byte strings."""
    store = PMKStore(str(tmp_path))
    words = [b"matrix-%03d" % i for i in range(5)]
    cols = np.stack(
        [np.frombuffer(_pmk(w), dtype=">u4").astype(np.uint32)
         for w in words], axis=1)
    store.put(ESSID, words, cols)
    assert store.lookup(ESSID, words) == [_pmk(w) for w in words]


def test_reopen_persists_and_serves_from_mmap(tmp_path):
    store = PMKStore(str(tmp_path))
    words = [b"persist-%03d" % i for i in range(32)]
    _seed(store, words)
    store.close()
    back = PMKStore(str(tmp_path))
    assert back.lookup(ESSID, words) == [_pmk(w) for w in words]


def test_torn_tail_skipped_not_fatal(tmp_path):
    """Fault injection: a segment truncated mid-record (a crash tearing
    the last appended frame) must open cleanly, skip the torn tail, and
    keep serving every record of the intact frames."""
    store = PMKStore(str(tmp_path))
    first = [b"intact-%03d" % i for i in range(8)]
    torn = [b"torn-%03d" % i for i in range(8)]
    _seed(store, first)   # frame 1
    _seed(store, torn)    # frame 2 — about to be torn
    store.close()
    edir = os.path.join(str(tmp_path), ESSID.hex())
    seg = os.path.join(edir, sorted(os.listdir(edir))[-1])
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.truncate(size - 17)  # mid-record: not a frame boundary
    back = PMKStore(str(tmp_path))
    assert back.lookup(ESSID, first) == [_pmk(w) for w in first]
    assert all(p is None for p in back.lookup(ESSID, torn))
    # and the store still accepts writes after the repair-by-skip
    _seed(back, torn)
    assert back.lookup(ESSID, torn) == [_pmk(w) for w in torn]


def test_corrupt_frame_crc_skipped(tmp_path):
    """A flipped byte inside the tail frame (torn write, bit rot) fails
    the CRC and drops that frame only."""
    store = PMKStore(str(tmp_path))
    first = [b"crc-ok-%03d" % i for i in range(4)]
    bad = [b"crc-bad-%03d" % i for i in range(4)]
    _seed(store, first)
    _seed(store, bad)
    store.close()
    edir = os.path.join(str(tmp_path), ESSID.hex())
    seg = os.path.join(edir, sorted(os.listdir(edir))[-1])
    with open(seg, "r+b") as f:
        f.seek(os.path.getsize(seg) - 5)
        f.write(b"\xff")
    back = PMKStore(str(tmp_path))
    assert back.lookup(ESSID, first) == [_pmk(w) for w in first]
    assert all(p is None for p in back.lookup(ESSID, bad))


def test_rotation_and_eviction_under_cap(tmp_path):
    """Segments rotate at segment_bytes and the OLDEST sealed segments
    are evicted once the total passes max_bytes — the earliest records
    stop hitting, the newest keep serving, and the bytes gauge tracks."""
    reg = MetricsRegistry()
    # tiny geometry: ~25 records per segment, cap at ~4 segments
    store = PMKStore(str(tmp_path), max_bytes=4096, segment_bytes=1024,
                     registry=reg)
    batches = [[b"evict-%02d-%03d" % (b, i) for i in range(16)]
               for b in range(12)]
    for batch in batches:
        _seed(store, batch)
    assert reg.value("dwpa_pmkstore_evictions_total") > 0
    assert reg.value("dwpa_pmkstore_bytes") <= 4096 + 1024  # cap + open seg
    assert all(p is None for p in store.lookup(ESSID, batches[0]))
    assert store.lookup(ESSID, batches[-1]) == [_pmk(w) for w in batches[-1]]
    # on-disk state agrees after reopen
    store.close()
    back = PMKStore(str(tmp_path), max_bytes=4096, segment_bytes=1024)
    assert back.lookup(ESSID, batches[-1]) == [_pmk(w) for w in batches[-1]]


def test_hit_miss_counters_and_ratio(tmp_path):
    reg = MetricsRegistry()
    store = PMKStore(str(tmp_path), registry=reg)
    words = [b"metric-%03d" % i for i in range(10)]
    _seed(store, words[:5])
    store.lookup(ESSID, words)
    assert reg.value("dwpa_pmkstore_hits_total") == 5
    assert reg.value("dwpa_pmkstore_misses_total") == 5
    assert reg.value("dwpa_pmkstore_hit_ratio") == pytest.approx(0.5)
    assert reg.value("dwpa_pmkstore_writes_total") == 5
    text = reg.render_prometheus()
    for name in ("dwpa_pmkstore_hits_total", "dwpa_pmkstore_misses_total",
                 "dwpa_pmkstore_hit_ratio", "dwpa_pmkstore_bytes"):
        assert name in text


# ---------------------------------------------------------------------------
# split stage: width buckets + framed-slice sharding
# ---------------------------------------------------------------------------


def test_miss_widths_bounded_and_mesh_aligned():
    for batch, n in ((2048, 8), (64, 8), (32, 8), (16, 8), (4096, 4)):
        widths = miss_widths(batch, n)
        assert 1 <= len(widths) <= 3
        assert widths[-1] == batch
        assert all(w % n == 0 and w > 0 for w in widths)
        # every miss count lands in exactly one static width
        for m in range(batch + 1):
            assert miss_width(batch, n, m) in widths
            assert miss_width(batch, n, m) >= m


def test_framed_slices_shard_the_store(tmp_path):
    """The multi-host property the store leans on: each host's feed
    framing hands it a disjoint slice of the global stream, so per-host
    stores (write-back of own-slice PMKs only) shard the cache with no
    coordination — disjoint contents, union = the whole stream."""
    words = [b"shard-%04d" % i for i in range(70)]  # ragged tail
    stores = []
    for pid in range(2):
        st = PMKStore(str(tmp_path / f"host{pid}"), pid=pid)
        for blk in frame_blocks(iter(words), 16, nproc=2, pid=pid):
            mine = [w for w in blk.words if w != b""]
            _seed(st, mine)
        stores.append(st)
    hit0 = {w for w in words if stores[0].lookup(ESSID, [w])[0] is not None}
    hit1 = {w for w in words if stores[1].lookup(ESSID, [w])[0] is not None}
    assert hit0 & hit1 == set()
    assert hit0 | hit1 == set(words)


# ---------------------------------------------------------------------------
# engine mixed-block parity vs the oracle
# ---------------------------------------------------------------------------

PSK = b"store-psk-777"


def _engine(store, psk=PSK, essid=ESSID, batch=32, seed="pmks-1"):
    line = synth.make_pmkid_line(psk, essid, seed=seed)
    return M22000Engine([line], batch_size=batch, pmk_store=store)


def test_all_miss_blocks_write_back_oracle_pmks(tmp_path):
    """Cold store: every block takes the all-miss path (plain shapes),
    the PSK still cracks, and the write-back leaves oracle-exact PMKs
    for EVERY candidate of the stream."""
    store = PMKStore(str(tmp_path))
    words = [b"coldword-%04d" % i for i in range(63)] + [PSK]
    founds = _crack(_engine(store), words)
    assert [f.psk for f in founds] == [PSK]
    assert store.lookup(ESSID, words) == [_pmk(w) for w in words]


def test_all_hit_blocks_use_cached_pmks(tmp_path):
    """Warm store: with every candidate cached the engine dispatches no
    PBKDF2 at all — and the find must still come out, through the cached
    PMK matrix."""
    store = PMKStore(str(tmp_path))
    words = [b"warmword-%04d" % i for i in range(63)] + [PSK]
    _seed(store, words)
    reg = MetricsRegistry()
    founds = _crack(_engine(store), words, registry=reg)
    assert [f.psk for f in founds] == [PSK]


def test_all_hit_path_trusts_the_cache(tmp_path):
    """Negative control proving the cache is actually used: poison the
    PSK's cached PMK and the device check (which sees only the cached
    matrix) must NOT report the find a recompute would have."""
    store = PMKStore(str(tmp_path))
    words = [b"poison-%04d" % i for i in range(63)] + [PSK]
    _seed(store, words[:-1])
    store.put(ESSID, [PSK], [b"\x00" * 32])  # wrong PMK for the PSK
    founds = _crack(_engine(store), words)
    assert founds == []


def test_interleaved_hit_miss_parity(tmp_path):
    """Mixed blocks: the planted PSK cracks whether it sits in the hit
    partition or the miss partition of its block, and the miss PMKs
    written back match the oracle."""
    for in_hits in (True, False):
        store = PMKStore(str(tmp_path / f"hit{in_hits}"))
        words = [b"mixword-%04d" % i for i in range(63)] + [PSK]
        seeded = [w for i, w in enumerate(words) if i % 2 == 0 and w != PSK]
        if in_hits:
            seeded.append(PSK)
        _seed(store, seeded)
        founds = _crack(_engine(store), words)
        assert [f.psk for f in founds] == [PSK], f"in_hits={in_hits}"
        assert store.lookup(ESSID, words) == [_pmk(w) for w in words]


def test_multi_essid_groups_split_independently(tmp_path):
    """Two ESSID groups over one stream: one group all-hit, the other
    all-miss — both nets crack, and each group's write-back lands under
    its own ESSID."""
    store = PMKStore(str(tmp_path))
    e2 = b"OtherStoreNet"
    psk2 = b"store-psk-888"
    words = [b"dualword-%04d" % i for i in range(62)] + [PSK, psk2]
    _seed(store, words)  # ESSID fully cached; e2 fully cold
    lines = [synth.make_pmkid_line(PSK, ESSID, seed="du1"),
             synth.make_pmkid_line(psk2, e2, seed="du2")]
    eng = M22000Engine(lines, batch_size=32, pmk_store=store)
    founds = _crack(eng, words)
    assert sorted(f.psk for f in founds) == sorted([PSK, psk2])
    assert store.lookup(e2, words) == [_pmk(w, e2) for w in words]


def test_resume_skip_across_cached_blocks(tmp_path):
    """A resumed unit fast-forwards the feed PAST cached blocks without
    disturbing the count contract: consumed sums to exactly the
    unskipped tail, and a PSK behind a mix of cached/uncached blocks
    still cracks."""
    store = PMKStore(str(tmp_path))
    words = [b"resume-%04d" % i for i in range(127)] + [PSK]
    _seed(store, words[:64])      # the skipped prefix is (mostly) cached
    _seed(store, words[96:112:2])  # one later block mixed
    skip = 48
    consumed = []
    founds = _crack(_engine(store), words, skip=skip,
                    on_batch=lambda c, f: consumed.append(c))
    assert [f.psk for f in founds] == [PSK]
    assert sum(consumed) == len(words) - skip
    # everything the tail touched is cached now, oracle-exact
    assert store.lookup(ESSID, words[skip:]) == \
        [_pmk(w) for w in words[skip:]]


def test_mixed_widths_recompile_bounded(tmp_path, recompile_sentinel):
    """The static-width proof: after one warmup per bucket, blocks at
    ANY hit/miss ratio (all-hit included) reuse compiled programs —
    zero XLA activity across the sweep."""
    store = PMKStore(str(tmp_path))
    batch = 32
    eng = _engine(store, batch=batch, seed="sentinel")
    widths = miss_widths(batch, eng.mesh.size)
    assert len(widths) <= 3
    n = 0

    def block(nmiss):
        """One full block with exactly ``nmiss`` uncached words (fixed
        8-char length so the column-trim width stays constant)."""
        nonlocal n
        ws = [b"sw%03d%03d" % (n, i) for i in range(batch)]
        n += 1
        _seed(store, ws[nmiss:])
        return ws

    # warm every static width once (and the all-hit path)
    for m in list(widths) + [0]:
        _crack(eng, block(min(m, batch)))
    with recompile_sentinel(allowed=0, label="mixed width sweep"):
        for m in (1, 3, 7, 9, 15, 20, 31, 0, batch):
            _crack(eng, block(min(m, batch)))


def test_pmkstore_metrics_through_engine(tmp_path):
    """The engine wiring records to the store's registry: a cold+warm
    pair shows misses, then hits, then a live ratio — the
    dwpa_pmkstore_* family the README documents."""
    reg = MetricsRegistry()
    store = PMKStore(str(tmp_path), registry=reg)
    words = [b"obsword-%04d" % i for i in range(31)] + [PSK]
    _crack(_engine(store), words, registry=reg)
    assert reg.value("dwpa_pmkstore_misses_total") >= len(words)
    assert reg.value("dwpa_pmkstore_writes_total") == len(words)
    _crack(_engine(store, seed="pmks-2"), words, registry=reg)
    assert reg.value("dwpa_pmkstore_hits_total") >= len(words)
    assert 0 < reg.value("dwpa_pmkstore_hit_ratio") < 1
