"""Differential tests: device m22000 engine vs the pure-Python oracle.

Fixtures are synthesized (dwpa_tpu/testing.py) with known PSKs, mirroring
the role of the reference client's hardcoded known-PSK challenge gate
(help_crack/help_crack.py:690-725): the engine must crack them from a small
dictionary and agree with the oracle on (psk, nc, endian, pmk).
"""

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.models import hashline as hl
from dwpa_tpu.models.m22000 import M22000Engine
from dwpa_tpu.oracle import m22000 as oracle

BATCH = 64


def small_dict(*planted):
    words = [f"word{i:04d}xx".encode() for i in range(40)]
    out = []
    for i, w in enumerate(words):
        out.append(w)
        for j, p in enumerate(planted):
            if i == 7 * (j + 1):
                out.append(p)
    return out


def crack_one(line, psk):
    eng = M22000Engine([line], batch_size=BATCH)
    founds = eng.crack(small_dict(psk))
    assert len(founds) == 1
    f = founds[0]
    assert f.psk == psk
    # oracle must agree bit-for-bit (pmk + nc semantics)
    o = oracle.check_key_m22000(line, [psk])
    assert o is not None
    assert f.pmk == o[3]
    return f


def test_pmkid_crack():
    psk = b"s3cretpass"
    f = crack_one(tfx.make_pmkid_line(psk, b"TestNet"), psk)
    assert f.nc == 0 and f.endian == ""


@pytest.mark.parametrize("keyver", [1, 2, 3])
def test_eapol_exact(keyver):
    psk = b"hunter2hunter2"
    line = tfx.make_eapol_line(psk, b"MyWifi", keyver=keyver, seed=f"kv{keyver}")
    f = crack_one(line, psk)
    assert f.nc == 0


@pytest.mark.parametrize("delta,endian", [(3, "LE"), (-2, "BE")])
def test_eapol_nonce_error_correction(delta, endian):
    psk = b"correcthorse"
    line = tfx.make_eapol_line(
        psk, b"NCNet", keyver=2, nc_delta=delta, endian=endian, seed=f"nc{delta}{endian}"
    )
    f = crack_one(line, psk)
    assert (f.nc, f.endian) == (delta, endian)
    o = oracle.check_key_m22000(line, [psk])
    assert (o[1], o[2]) == (delta, endian)


def test_apless_message_pair_skips_nc():
    psk = b"exactonly1"
    # ap-less: nonce taken from the AP's own M1, NC must not be searched
    line = tfx.make_eapol_line(
        psk, b"ApLess", keyver=2, message_pair=hl.MP_APLESS, seed="apless"
    )
    eng = M22000Engine([line], batch_size=BATCH)
    assert len(eng.nets[0].variants) == 1
    assert eng.crack(small_dict(psk))[0].psk == psk

    # same net but NC-shifted: gated engine must NOT find it
    shifted = tfx.make_eapol_line(
        psk, b"ApLess", keyver=2, nc_delta=2, endian="LE",
        message_pair=hl.MP_APLESS, seed="apless2",
    )
    shifted = shifted[:-2] + "10"  # keep only the ap-less bit (clear NC-needed)
    eng2 = M22000Engine([shifted], batch_size=BATCH)
    assert eng2.crack(small_dict(psk)) == []


def test_endian_gating_bits():
    psk = b"legatedpass"
    line = tfx.make_eapol_line(
        psk, b"LeNet", keyver=2, nc_delta=1, endian="LE",
        message_pair=hl.MP_LE, seed="gate-le",
    )
    eng = M22000Engine([line], batch_size=BATCH)
    # LE-gated: every non-exact variant must be LE
    assert all(e == "LE" for d, e in eng.nets[0].variants if d != 0)
    assert eng.crack(small_dict(psk))[0].nc == 1


def test_essid_grouping_multi_net():
    essid = b"SharedESSID"
    psk1, psk2 = b"password-one", b"password-two"
    lines = [
        tfx.make_eapol_line(psk1, essid, keyver=2, seed="g1"),
        tfx.make_eapol_line(psk2, essid, keyver=2, seed="g2"),
        tfx.make_pmkid_line(psk1, essid, seed="g3"),
    ]
    eng = M22000Engine(lines, batch_size=BATCH)
    assert len(eng.groups) == 1  # one PBKDF2 pass serves all three nets
    founds = eng.crack(small_dict(psk1, psk2))
    assert sorted(f.psk for f in founds) == sorted([psk1, psk1, psk2])
    assert not eng.groups  # all nets cracked and retired


def test_wrong_passwords_find_nothing():
    line = tfx.make_eapol_line(b"rightpass99", b"NoNet", keyver=2, seed="none")
    eng = M22000Engine([line], batch_size=BATCH)
    assert eng.crack(small_dict()) == []


def test_short_candidates_filtered():
    psk = b"okpass88"
    eng = M22000Engine([tfx.make_pmkid_line(psk, b"Len")], batch_size=BATCH)
    founds = eng.crack([b"short", b"x" * 64, psk])
    assert [f.psk for f in founds] == [psk]


def test_randomized_differential_vs_oracle():
    """Seeded fuzz: random (keyver, NC delta/endian, hint bits, essid and
    psk lengths incl. binary bytes) configurations must crack on device
    exactly when the oracle accepts them, with matching PMK/nc/endian."""
    import random

    rng = random.Random(0xD3AD)
    lines, psks = [], []
    for i in range(14):
        essid = bytes(rng.randrange(1, 256) for _ in range(rng.randrange(1, 33)))
        psk = bytes(rng.randrange(1, 256) for _ in range(rng.randrange(8, 64)))
        if rng.random() < 0.3:
            line = tfx.make_pmkid_line(psk, essid, seed=f"fz{i}")
        else:
            keyver = rng.choice([1, 2, 3])
            delta = rng.choice([0, 0, 1, -1, 2, -2, 4, -4, 5])
            endian = rng.choice(["LE", "BE"])
            mp = 0
            if delta and rng.random() < 0.5:
                mp |= hl.MP_LE if endian == "LE" else hl.MP_BE
            line = tfx.make_eapol_line(psk, essid, keyver=keyver,
                                     nc_delta=delta, endian=endian,
                                     message_pair=mp, seed=f"fz{i}")
        lines.append(line)
        psks.append(psk)

    eng = M22000Engine(lines, batch_size=32)
    chaff = [bytes(rng.randrange(1, 256) for _ in range(10)) for _ in range(40)]
    founds = eng.crack(chaff + psks)
    by_line = {f.line.raw: f for f in founds}
    assert len(founds) == len(lines)
    for line, psk in zip(lines, psks):
        f = by_line[line]
        ref = oracle.check_key_m22000(hl.parse(line), [psk])
        assert ref is not None
        assert (f.psk, f.pmk) == (psk, ref[3])
        assert f.nc == (ref[1] or 0)
        assert (f.endian or "") == (ref[2] or "")
