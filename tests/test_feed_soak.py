"""Slow soak: a loopback work unit through the candidate feed with a
fault-injecting producer.

Tier-1 runs the fast feed units (tests/test_feed.py); this soak —
``-m slow``, ~30 s — drives the FULL client path (process_work over the
in-process WSGI server) against a dictionary big enough for many feed
blocks, kills the producer mid-stream once, and asserts the crash
contract end to end: the FeedError carries a stream offset, no feed
threads survive, the resume checkpoint holds a valid block-aligned
mid-unit offset, and the revived unit fast-forwards from exactly there
— skipped + retried re-covers the deterministic stream with no gap and
no double-count — and still cracks the planted PSK.
"""

import gzip
import hashlib
import os
import threading

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.client.main import ClientConfig, TpuCrackClient
from dwpa_tpu.feed import FeedError
from dwpa_tpu.obs import MetricsRegistry
from dwpa_tpu.server import Database, ServerCore, make_wsgi_app

from test_client_loopback import LoopbackAPI

pytestmark = pytest.mark.slow

PSK = b"soak-psk-2024"
ESSID = b"SoakNet"
BATCH = 64
WORDS = 4096       # many feed blocks; the PSK sits at the very end
FAULT_AT = WORDS // 2  # dict-stream index where the producer dies once


def _feed_threads():
    return [t for t in threading.enumerate() if t.name.startswith("dwpa-feed")]


class FaultyDictStream:
    """DictStream twin that raises once, mid-stream — the
    fault-injecting producer (the feed's producer thread is what
    executes this iterator)."""

    armed = False

    def __init__(self, source, **kw):
        from dwpa_tpu.gen import DictStream

        self._real = DictStream(source, **kw)

    def __iter__(self):
        cls = type(self)
        for i, w in enumerate(self._real):
            if cls.armed and i == FAULT_AT:
                cls.armed = False
                raise OSError("injected producer fault")
            yield w


@pytest.fixture
def server(tmp_path):
    core = ServerCore(Database(":memory:"),
                      dictdir=str(tmp_path / "dicts"),
                      capdir=str(tmp_path / "caps"))
    os.makedirs(core.dictdir, exist_ok=True)
    words = [b"soakword-%06d" % i for i in range(WORDS - 1)] + [PSK]
    blob = gzip.compress(b"\n".join(words) + b"\n")
    with open(os.path.join(core.dictdir, "soak.txt.gz"), "wb") as f:
        f.write(blob)
    core.add_hashlines([tfx.make_pmkid_line(PSK, ESSID, seed="soak1")])
    core.add_dict("dict/soak.txt.gz", "soak.txt.gz",
                  hashlib.md5(blob).hexdigest(), len(words), rules=None)
    core.db.x("UPDATE nets SET algo = ''")
    return core


def _release_net(server):
    server.db.x("UPDATE nets SET n_state = 0, pass = NULL, algo = ''")


def _client(server, workdir, **cfg_kw):
    cfg = ClientConfig(base_url="http://loopback/", workdir=str(workdir),
                       batch_size=BATCH, dictcount=1, **cfg_kw)
    api = LoopbackAPI(make_wsgi_app(server))
    return TpuCrackClient(cfg, api=api, log=lambda *a, **k: None,
                          registry=MetricsRegistry())


def test_soak_fault_mid_stream_then_resume(server, tmp_path, monkeypatch):
    import dwpa_tpu.client.main as cm

    # -- session A: clean reference run fixes the unit's deterministic
    # candidate total (pass-1 targeted stream + the dict)
    clean = _client(server, tmp_path / "work_a")
    work = clean.api.get_work(1)
    res_a = clean.process_work(dict(work))
    assert res_a.accepted and [f.psk for f in res_a.founds] == [PSK]
    total = res_a.candidates_tried
    assert total >= WORDS  # pass 1 contributes on top of the dict

    # -- session B: same unit, fault-injecting producer
    _release_net(server)
    monkeypatch.setattr(cm, "DictStream", FaultyDictStream)
    FaultyDictStream.armed = True
    crashed = _client(server, tmp_path / "work_b")
    work_b = crashed.api.get_work(1)
    with pytest.raises(FeedError) as e:
        crashed.process_work(dict(work_b))
    assert not FaultyDictStream.armed  # fired exactly once
    assert isinstance(e.value.__cause__, OSError)
    # the fault names the failing block's pass-2 stream offset: at or
    # before the injected word index, at most one block earlier
    assert FAULT_AT - BATCH <= e.value.offset <= FAULT_AT
    assert "offset" in str(e.value)
    # clean teardown: no orphan producer threads survive the crash
    assert not _feed_threads()

    # the resume checkpoint survived with a mid-unit offset: a true
    # prefix of the stream, never regressed to zero, never past the
    # fault (pass-1 candidates precede the dict in the global count)
    snap = crashed._read_resume()
    assert snap is not None and snap["hkey"] == work_b["hkey"]
    done = snap["_progress"]["done"]
    assert 0 < done < total

    # -- session C: revive from B's workdir; the unit fast-forwards
    # from the checkpoint and the remainder EXACTLY covers the stream
    # (deterministic framing: skipped + retried == total, no gap, no
    # double count)
    revived = _client(server, tmp_path / "work_b")
    replay = revived._read_resume()
    assert replay is not None and replay["_progress"]["done"] == done
    res_c = revived.process_work(replay)
    assert res_c.accepted
    assert [f.psk for f in res_c.founds] == [PSK]
    assert res_c.candidates_tried == total - done
    assert revived.registry.value("dwpa_client_resume_skipped_total") == done
    assert not _feed_threads()
    assert not os.path.exists(revived.resume_path)
    row = server.db.q1("SELECT n_state, pass FROM nets")
    assert row["n_state"] == 1 and row["pass"] == PSK
    # the pass-2 feed telemetry is live in the client registry
    assert revived.registry.value("dwpa_feed_blocks_total", feed="pass2") >= 1


def test_soak_steady_unit_with_multiworker_feed(server, tmp_path):
    """No-fault soak at feed_workers=2: a whole unit's dict streams
    through two producers and the unit completes exactly as with one
    (the feed's reorder buffer keeps stream order regardless of thread
    timing)."""
    client = _client(server, tmp_path / "work2", feed_workers=2,
                     feed_depth=3)
    work = client.api.get_work(1)
    res = client.process_work(work)
    assert res.accepted
    assert [f.psk for f in res.founds] == [PSK]
    assert res.candidates_tried >= WORDS
    assert not _feed_threads()
