"""Runtime lock-order witness (dwpa_tpu.analysis.lockwatch).

The witness is proven both ways, like its static twin DW301: a seeded
acquisition-order cycle it must catch (naming the offending edges), and
the consistent-order / reentrant idioms it must stay silent on — plus
the patch/restore contract of ``watch_locks`` and the Condition
protocol the feed's ``_cv`` depends on.
"""

import threading

import pytest

from dwpa_tpu.analysis.lockwatch import (
    LockOrderError, LockWitness, WatchedLock, WatchedRLock, watch_locks,
    witness_report)


def _run(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(10)
    assert not t.is_alive()


# -- witness graph ----------------------------------------------------------


def test_witness_records_ordered_acquisitions():
    w = LockWitness()
    a = WatchedLock(w, name="A")
    b = WatchedLock(w, name="B")
    with a:
        with b:
            pass
    assert ("A", "B") in w.edges
    assert ("B", "A") not in w.edges
    w.check()  # consistent order: no cycle


def test_witness_cycle_detected_and_edges_named():
    w = LockWitness(label="seeded")
    a = WatchedLock(w, name="A")
    b = WatchedLock(w, name="B")
    with a:
        with b:
            pass

    def invert():
        with b:
            with a:
                pass

    _run(invert)  # other thread, so no actual deadlock — just the edge
    with pytest.raises(LockOrderError) as exc:
        w.check()
    msg = str(exc.value)
    assert "A -> B" in msg and "B -> A" in msg
    assert "seeded" in msg
    assert "DW301" in msg  # points at the static twin


def test_witness_report_lists_edges():
    w = LockWitness()
    assert "no ordered acquisitions" in witness_report(w)
    a = WatchedLock(w, name="A")
    b = WatchedLock(w, name="B")
    with a, b:
        pass
    rep = witness_report(w)
    assert "A -> B" in rep and "1 ordered acquisition edge" in rep


def test_rlock_reentry_records_no_self_edge():
    w = LockWitness()
    r = WatchedRLock(w, name="R")
    other = WatchedLock(w, name="O")
    with r:
        with other:
            with r:  # reentrant: must not create O -> R
                pass
    assert w.edges == {("R", "O"): threading.current_thread().name}
    w.check()


def test_rlock_depth_and_foreign_release_guard():
    w = LockWitness()
    r = WatchedRLock(w, name="R")
    r.acquire()
    r.acquire()
    r.release()
    assert r.locked()
    r.release()
    assert not r.locked()
    with pytest.raises(RuntimeError):
        r.release()


def test_condition_over_watched_rlock():
    """The feed's _cv shape: a Condition built over the watched RLock
    waits and wakes correctly, and the post-wait re-acquisition is
    recorded as a real ordering event."""
    w = LockWitness()
    cv = threading.Condition(WatchedRLock(w, name="CV"))
    hits = []

    def consumer():
        with cv:
            while not hits:
                cv.wait(timeout=5)
        hits.append("consumed")

    t = threading.Thread(target=consumer)
    t.start()
    import time

    time.sleep(0.05)
    with cv:
        hits.append("produced")
        cv.notify_all()
    t.join(10)
    assert hits == ["produced", "consumed"]
    w.check()


# -- the patch window -------------------------------------------------------


def test_watch_locks_patches_and_restores():
    real_lock, real_rlock = threading.Lock, threading.RLock
    with watch_locks(label="win") as witness:
        lk = threading.Lock()
        rk = threading.RLock()
        assert isinstance(lk, WatchedLock)
        assert isinstance(rk, WatchedRLock)
        with lk:
            with rk:
                pass
    assert threading.Lock is real_lock
    assert threading.RLock is real_rlock
    assert len(witness.edges) == 1


def test_watch_locks_raises_on_cycle_at_exit():
    with pytest.raises(LockOrderError):
        with watch_locks():
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass

            def invert():
                with b:
                    with a:
                        pass

            _run(invert)


def test_watch_locks_does_not_mask_body_exception():
    real_lock = threading.Lock
    with pytest.raises(ValueError):
        with watch_locks():
            a = threading.Lock()
            b = threading.Lock()
            with a, b:
                pass

            def invert():
                with b, a:
                    pass

            _run(invert)  # cycle present, but the body error wins
            raise ValueError("body failure")
    assert threading.Lock is real_lock


def test_queue_internals_created_inside_window_are_watched():
    """queue.Queue built in the window uses the patched factories, so
    producer/consumer lock order shows up in the witness for free."""
    import queue

    with watch_locks() as witness:
        q = queue.Queue()
        outer = threading.Lock()
        with outer:
            q.put(1)          # q.mutex acquired while holding outer
        assert q.get() == 1
    assert any(b == "unknown" or "Lock" in b
               for (_, b) in witness.edges), witness.edges
