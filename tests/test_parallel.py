"""Mesh-sharded crack step on the 8-device virtual CPU mesh.

Exercises the product multi-chip path (parallel/build_crack_step): the
candidate axis split over the "dp" mesh axis, per-shard PBKDF2+verify,
and the psum hits-gate — the TPU mapping of the reference's volunteer
data-parallel work distribution (web/content/get_work.php:96-135).
"""

import jax
import numpy as np

from dwpa_tpu import testing as T
from dwpa_tpu.models import hashline as hl
from dwpa_tpu.models import m22000 as m
from dwpa_tpu.parallel import build_crack_step, default_mesh, shard_candidates
from dwpa_tpu.utils import bytesops as bo

ESSID = b"mesh-essid"
PSK = b"meshpass42"


def _nets():
    return [
        m.prep_net(hl.parse(T.make_pmkid_line(PSK, ESSID, seed="mp1"))),
        m.prep_net(hl.parse(T.make_eapol_line(PSK, ESSID, keyver=2, seed="mp2"))),
        m.prep_net(
            hl.parse(
                T.make_eapol_line(PSK, ESSID, keyver=2, nc_delta=3, endian="LE", seed="mp3")
            )
        ),
    ]


def _batch(n):
    pws = [b"filler%04d" % i for i in range(n)]
    pws[n // 2] = PSK
    return pws


def test_crack_step_on_8_device_mesh():
    mesh = default_mesh()
    assert mesh.size == 8
    nets = _nets()
    s1, s2 = m.essid_salt_blocks(ESSID)
    step = build_crack_step(mesh, nets, s1, s2)

    batch = 16
    pws = _batch(batch)
    pw_words = shard_candidates(mesh, bo.pack_passwords_be(pws))
    hits, found, pmk = jax.block_until_ready(step(pw_words))
    assert int(hits) == 3  # one match per net (exact, exact, NC+3)
    # the sharded PMK comes back reassembled and matches the oracle
    from dwpa_tpu.oracle.m22000 import pmk_from_psk

    got = bo.words_to_bytes_be(np.array(pmk)[:, batch // 2])
    assert got == pmk_from_psk(PSK, ESSID)
    found = np.array(found)
    # the planted PSK's column holds every hit; no other column matches
    assert found[:, :, batch // 2].any(axis=1).all()
    found[:, :, batch // 2] = False
    assert not found.any()


def test_crack_step_matches_single_device():
    """Same founds on the full mesh and a 1-device mesh (determinism)."""
    nets = _nets()
    s1, s2 = m.essid_salt_blocks(ESSID)
    pws = _batch(8)
    pw_words = bo.pack_passwords_be(pws)

    mesh8 = default_mesh()
    step8 = build_crack_step(mesh8, nets, s1, s2)
    _, found8, _ = step8(shard_candidates(mesh8, pw_words))

    mesh1 = default_mesh(n=1)
    step1 = build_crack_step(mesh1, nets, s1, s2)
    _, found1, _ = step1(shard_candidates(mesh1, pw_words))

    np.testing.assert_array_equal(np.array(found8), np.array(found1))


def test_engine_identical_founds_on_1_and_8_device_mesh():
    """The engine product path produces the same founds on any mesh."""
    lines = [
        T.make_pmkid_line(PSK, ESSID, seed="me1"),
        T.make_eapol_line(PSK, ESSID, keyver=2, nc_delta=2, endian="BE", seed="me2"),
    ]
    results = {}
    for n in (1, 8):
        eng = m.M22000Engine(lines, batch_size=16, mesh=default_mesh(n=n))
        founds = eng.crack(_batch(16))
        results[n] = sorted(
            (f.line.pmkid_or_mic, f.psk, f.nc, f.endian, f.pmk) for f in founds
        )
    assert len(results[1]) == 2
    assert results[1] == results[8]


def test_engine_oversize_batch_pads_to_mesh_multiple():
    """A caller-supplied batch larger than batch_size still shards evenly."""
    lines = [T.make_pmkid_line(PSK, ESSID, seed="ob1")]
    eng = m.M22000Engine(lines, batch_size=8, mesh=default_mesh())
    pws = _batch(16) + [b"extra-%02d" % i for i in range(4)]  # 20 candidates
    founds = eng.crack_batch(pws)
    assert [f.psk for f in founds] == [PSK]


def test_multihost_mesh_single_process():
    """Single-process degenerate case: spans all local devices; the
    same dp axis the crack step shards over."""
    from dwpa_tpu.parallel import multihost_mesh

    mesh = multihost_mesh()
    assert mesh.axis_names == ("dp",)
    assert mesh.size == len(jax.devices())


def test_crack_step_bucket_pad_and_reorder():
    """3 same-signature EAPOL nets (bucket-padded to 4) interleaved with
    a PMKID net: exercises the _pad_nets dup-row branch (hits must stay
    an exact count) and the found-row order restoration (each found row
    must belong to the net at that index of the input list)."""
    mesh = default_mesh()
    nets = [
        m.prep_net(hl.parse(T.make_eapol_line(PSK, ESSID, keyver=2, seed="br1"))),
        m.prep_net(hl.parse(T.make_pmkid_line(PSK, ESSID, seed="br2"))),
        m.prep_net(hl.parse(T.make_eapol_line(PSK, ESSID, keyver=2, seed="br3"))),
        m.prep_net(
            hl.parse(
                T.make_eapol_line(
                    PSK, ESSID, keyver=2, nc_delta=2, endian="LE", seed="br4"
                )
            )
        ),
    ]
    s1, s2 = m.essid_salt_blocks(ESSID)
    step = build_crack_step(mesh, nets, s1, s2)
    batch = 16
    hits, found, _ = jax.block_until_ready(
        step(shard_candidates(mesh, bo.pack_passwords_be(_batch(batch))))
    )
    assert int(hits) == 4  # exact: bucket-pad dup rows masked out
    found = np.array(found)
    assert found.shape[0] == 4
    # every net matches exactly the planted column; the PMKID net's row
    # (input index 1) must be the 1-variant row — order was restored
    assert found[:, :, batch // 2].any(axis=1).all()
    assert found[1, 0, batch // 2] and not found[1, 1:, :].any()
    found[:, :, batch // 2] = False
    assert not found.any()


def test_crack_mask_device_generated():
    """crack_mask: on-device iota->digits generation end to end, founds
    identical to the host-packed path, skip/limit slicing honored."""
    psk = b"77345678"  # inside ?d x8
    lines = [T.make_pmkid_line(psk, ESSID, seed="mk1"),
             T.make_eapol_line(psk, ESSID, keyver=2, seed="mk2")]
    eng = m.M22000Engine(lines, batch_size=64, mesh=default_mesh())
    founds = eng.crack_mask("?d?d?d?d?d?d?d?d", skip=77345600, limit=256)
    assert sorted(f.psk for f in founds) == [psk, psk]
    # a slice that excludes the PSK finds nothing
    eng2 = m.M22000Engine(lines, batch_size=64, mesh=default_mesh())
    assert eng2.crack_mask("?d?d?d?d?d?d?d?d", skip=0, limit=128) == []


def test_device_mask_words_matches_host_pack():
    from dwpa_tpu.gen.mask import device_mask_words, mask_words

    for mask, start in (("?d?d?d?d?d?d?d?d", 0),
                        ("?d?d?d?d?d?d?d?d", 99999980),
                        ("ab?l?d", 7),
                        ("?d?d?d?d?d?d?d?d?d?d", 9_999_999_000)):
        dev = np.array(device_mask_words(mask, start, 16))
        ref = bo.pack_passwords_be(list(mask_words(mask, skip=start, limit=16)))
        np.testing.assert_array_equal(dev, ref, err_msg=f"{mask}@{start}")
