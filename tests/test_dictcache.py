"""dwpa_tpu.feed.dictcache: the packed-dictionary cache.

Four layers under test:

- the chunked ``DictStream`` cold path (bit-identical word semantics
  vs a line-split oracle: lone ``\\r``, CRLF, blank lines, missing
  trailing newline, skip/limit, carry across chunk boundaries);
- the CACHE (cold/warm block parity word-for-word against the native
  packer, ``$HEX`` decode and the 63-byte boundary included;
  torn-tail and CRC fault injection -> cold fallback, never wrong
  words; dhash-mismatch invalidation; LRU eviction under the byte
  cap);
- the FRAMING twin (``frame_packed`` reproduces ``frame_blocks``
  geometry and per-host content on a multi-host mesh);
- the ENGINE warm path — a warm resume must produce the identical
  found list and consumed counts as the cold stream it replaced.
"""

import gzip
import os

import numpy as np
import pytest

from dwpa_tpu import testing as synth
from dwpa_tpu.feed import CandidateFeed, DictCache, DictFeedSource
from dwpa_tpu.feed.framing import frame_blocks, frame_packed
from dwpa_tpu.gen.dicts import DictStream, md5_file
from dwpa_tpu.models.m22000 import M22000Engine
from dwpa_tpu.native import pack_candidates_fast
from dwpa_tpu.obs import MetricsRegistry

HAVE_NATIVE = pack_candidates_fast([b"probeword"], 8, 63,
                                   capacity=1) is not None
needs_native = pytest.mark.skipif(
    not HAVE_NATIVE, reason="native packer unavailable — no warm path")


# ---------------------------------------------------------------------------
# DictStream chunked cold path vs the line-split oracle
# ---------------------------------------------------------------------------


def _oracle(blob, skip=0, limit=None):
    """The pre-chunking semantics: binary line iteration (split on
    ``\\n`` only), skip counts line indices INCLUDING blanks, limit
    counts yielded words, trailing ``\\r\\n`` runs stripped."""
    lines = blob.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    out = []
    for i, line in enumerate(lines):
        if i < skip:
            continue
        if limit is not None and len(out) >= limit:
            break
        w = line.rstrip(b"\r\n")
        if w:
            out.append(w)
    return out


EDGE_BLOBS = [
    b"",
    b"\n",
    b"\n\n\n",
    b"alpha\r\nbeta\n\ngamma",          # CRLF + blank + no trailing \n
    b"lone\rcarriage\nnext\n",          # lone \r stays inside its word
    b"tail-no-newline",
    b"x" * 63 + b"\n" + b"y" * 64 + b"\nok-word\n",
    b"a\n\rb\n\n\nc\r\r\n",             # leading \r kept, trailing run gone
    b"\n".join(b"w%04d" % i for i in range(257)),  # no trailing newline
]


@pytest.mark.parametrize("blob", EDGE_BLOBS, ids=range(len(EDGE_BLOBS)))
@pytest.mark.parametrize("skip,limit", [(0, None), (1, None), (3, 2),
                                        (0, 1), (5, None), (1000, None)])
def test_dictstream_matches_line_oracle(tmp_path, blob, skip, limit):
    path = os.path.join(str(tmp_path), "d.txt")
    with open(path, "wb") as f:
        f.write(blob)
    got = list(DictStream(path, skip=skip, limit=limit))
    assert got == _oracle(blob, skip, limit)


def test_dictstream_carry_across_tiny_chunks(tmp_path, monkeypatch):
    """Words spanning chunk boundaries reassemble exactly (CHUNK=5
    forces a carry on nearly every read), gzip included."""
    monkeypatch.setattr(DictStream, "CHUNK", 5)
    blob = b"alpha\r\nbeta\n\ngam\rma\nx" * 7 + b"final-no-nl"
    path = os.path.join(str(tmp_path), "d.gz")
    with gzip.open(path, "wb") as f:
        f.write(blob)
    assert list(DictStream(path)) == _oracle(blob)
    assert list(DictStream(path, skip=4, limit=3)) == _oracle(blob, 4, 3)


# ---------------------------------------------------------------------------
# cache: cold/warm parity, fault injection, invalidation, eviction
# ---------------------------------------------------------------------------

#: a word mix hitting every packer filter edge: $HEX decode (valid,
#: odd-digit, non-hex — the latter two served as literals), the 8/63
#: length boundaries, blanks dropped upstream by DictStream
WORDS = (
    [b"word-%05d-pw" % i for i in range(1500)]
    + [b"$HEX[70617373776f72643132]",   # decodes to "password12"
       b"$HEX[616263]",                 # decodes to 3 bytes: filtered
       b"$HEX[zzzz]",                   # non-hex: literal, len 10 ok
       b"short",                        # < 8: filtered
       b"x" * 63,                       # boundary: kept
       b"y" * 64,                       # boundary + 1: filtered
       b"eight888"]
)


def _dict_file(tmp_path, words, tag=b""):
    """Write a gz dict named ``<dhash>.gz`` (the client's on-disk
    naming) and return ``(path, dhash)``."""
    blob = b"\n".join(list(words) + ([tag] if tag else [])) + b"\n"
    tmp = os.path.join(str(tmp_path), "staging.gz")
    with gzip.open(tmp, "wb") as f:
        f.write(blob)
    dhash = md5_file(tmp)
    path = os.path.join(str(tmp_path), dhash + ".gz")
    os.replace(tmp, path)
    return path, dhash


def _collect(units, cache, bs=256, skip=0, nproc=1, pid=0):
    """Drain a DictFeedSource through CandidateFeed; returns
    ``[(offset, count, padded, (rows, lens, nvalid) | None, words)]``
    with materialized preps copied out of the mmap."""
    src = DictFeedSource(units, batch_size=bs, cache=cache, skip=skip,
                         nproc=nproc, pid=pid)
    feed = CandidateFeed(None, batch_size=bs, frames=src, producers=1,
                         prepack=None, registry=MetricsRegistry())
    out = []
    try:
        for blk in feed:
            prep = blk.prep
            if prep is not None:
                prep = (np.asarray(prep[0]).copy(),
                        np.asarray(prep[1]).copy(), prep[2])
            out.append((blk.offset, blk.count, blk.padded, prep,
                        list(blk.words)))
    finally:
        feed.close()
    return out, src.skipped


def _assert_parity(cold, warm, bs):
    """Warm blocks must carry exactly what the native packer produces
    for the corresponding cold block's words."""
    assert len(cold) == len(warm)
    for (co, cc, cp, _, cw), (wo, wc, wp, wprep, ww) in zip(cold, warm):
        assert (co, cc, cp) == (wo, wc, wp)
        assert ww == []                      # warm never decodes words
        packed = pack_candidates_fast(cw, 8, 63, capacity=bs)
        if packed is None:                   # all-filtered block
            assert wprep[2] == 0
            continue
        rows, lens, nv = packed
        assert nv == wprep[2]
        assert np.array_equal(np.asarray(rows), wprep[0])
        assert np.array_equal(np.asarray(lens[:nv], np.uint8),
                              wprep[1][:nv])


@needs_native
def test_cold_then_warm_word_for_word_parity(tmp_path):
    path, dhash = _dict_file(tmp_path, WORDS)
    reg = MetricsRegistry()
    cache = DictCache(os.path.join(str(tmp_path), "dc"), registry=reg)
    bs = 256
    cold, _ = _collect([(path, dhash)], cache, bs=bs)
    assert reg.value("dwpa_dictcache_miss_blocks_total") == len(cold)
    assert os.path.exists(cache._path(dhash))
    warm, _ = _collect([(path, dhash)], cache, bs=bs)
    assert reg.value("dwpa_dictcache_hit_blocks_total") == len(warm)
    assert reg.value("dwpa_dictcache_words_per_s", feed="warm") > 0
    _assert_parity(cold, warm, bs)


@needs_native
def test_warm_skip_is_an_index_seek_with_cold_parity(tmp_path):
    """Resume skips — mid-dict, across the dict boundary, beyond all
    words — produce identical blocks warm and cold, and identical
    ``skipped`` accounting."""
    p1, h1 = _dict_file(tmp_path, WORDS)
    p2, h2 = _dict_file(tmp_path, WORDS[:301], tag=b"second-dict")
    units = [(p1, h1), (p2, h2)]
    total = len(WORDS) + 302
    cache = DictCache(os.path.join(str(tmp_path), "dc"))
    bs = 256
    _collect(units, cache, bs=bs)  # populate
    for skip in (0, 100, len(WORDS) - 1, len(WORDS), len(WORDS) + 5,
                 total - 1, total, total + 99):
        cold, csk = _collect(units, None, bs=bs, skip=skip)
        warm, wsk = _collect(units, cache, bs=bs, skip=skip)
        assert csk == wsk == min(skip, total), skip
        _assert_parity(cold, warm, bs)
        if skip < total:
            assert warm[0][0] == skip


@needs_native
def test_torn_tail_falls_back_cold_with_correct_words(tmp_path):
    path, dhash = _dict_file(tmp_path, WORDS)
    cache = DictCache(os.path.join(str(tmp_path), "dc"))
    cold, _ = _collect([(path, dhash)], cache)
    entry = cache._path(dhash)
    size = os.path.getsize(entry)
    with open(entry, "r+b") as f:
        f.truncate(size - 13)          # mid-frame, not a boundary
    assert cache.reader(dhash) is None
    again, _ = _collect([(path, dhash)], cache)
    assert [b[4] for b in again] == [b[4] for b in cold]  # words intact


@needs_native
def test_crc_corruption_falls_back_cold(tmp_path):
    path, dhash = _dict_file(tmp_path, WORDS)
    cache = DictCache(os.path.join(str(tmp_path), "dc"))
    cold, _ = _collect([(path, dhash)], cache)
    entry = cache._path(dhash)
    with open(entry, "r+b") as f:
        f.seek(os.path.getsize(entry) // 2)  # inside some chunk payload
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    assert cache.reader(dhash) is None
    again, _ = _collect([(path, dhash)], cache)
    assert [b[4] for b in again] == [b[4] for b in cold]


@needs_native
def test_dhash_mismatch_invalidates(tmp_path):
    """An entry keyed to different dict bytes (the regenerated-dict
    case: same path shape, new dhash) must read as a miss — the
    embedded-dhash check, independent of the filename."""
    path, dhash = _dict_file(tmp_path, WORDS)
    cache = DictCache(os.path.join(str(tmp_path), "dc"))
    _collect([(path, dhash)], cache)
    other = "f" * 32
    os.replace(cache._path(dhash), cache._path(other))
    assert cache.reader(other) is None
    assert cache.reader(dhash) is None   # original file is gone too
    assert cache.reader("not-a-dhash") is None


@needs_native
def test_eviction_under_byte_cap_is_lru(tmp_path):
    reg = MetricsRegistry()
    cache = DictCache(os.path.join(str(tmp_path), "dc"), registry=reg)
    units = []
    for k in range(3):
        p, h = _dict_file(tmp_path, WORDS[:900], tag=b"evict-%d" % k)
        units.append((p, h))
        _collect([(p, h)], cache)
    sizes = {h: os.path.getsize(cache._path(h)) for _, h in units}
    # touch dict 0 so dict 1 becomes the LRU victim
    assert cache.reader(units[0][1]) is not None
    cache.max_bytes = sum(sizes.values()) - 1   # forces one eviction
    cache.evict()
    assert cache.reader(units[1][1]) is None    # LRU victim gone
    assert cache.reader(units[0][1]) is not None
    assert cache.reader(units[2][1]) is not None
    assert cache._bytes_used() <= cache.max_bytes
    assert reg.value("dwpa_dictcache_bytes") == cache._bytes_used()


@needs_native
def test_partial_consume_never_commits(tmp_path):
    """Breaking out of a cold stream mid-dict (fault, shutdown) must
    abort the cache write — a partial entry served warm would silently
    truncate the keyspace."""
    path, dhash = _dict_file(tmp_path, WORDS)
    cache = DictCache(os.path.join(str(tmp_path), "dc"))
    src = DictFeedSource([(path, dhash)], batch_size=64, cache=cache)
    for blk in src:
        break                            # consumer dies after one block
    assert cache.reader(dhash) is None
    assert not os.path.exists(cache._path(dhash))
    assert [f for f in os.listdir(cache.root) if ".tmp-" in f] == []


def test_native_packer_absent_stays_cold_and_correct(tmp_path):
    """Without the native packer there is nothing coherent to cache:
    writer() declines, no file appears, and the cold stream is
    untouched."""
    path, dhash = _dict_file(tmp_path, WORDS[:50])
    cache = DictCache(os.path.join(str(tmp_path), "dc"))
    cache._native_ok = False
    assert cache.writer(dhash) is None
    blocks, _ = _collect([(path, dhash)], cache)
    assert [w for b in blocks for w in b[4]] == WORDS[:50]
    assert not os.path.exists(cache._path(dhash))


# ---------------------------------------------------------------------------
# frame_packed: the multi-host framing twin
# ---------------------------------------------------------------------------


@needs_native
def test_frame_packed_matches_frame_blocks_per_host(tmp_path):
    """nproc=2: every host's warm blocks must carry the same geometry
    and packed content as its cold ``frame_blocks`` slice — the
    SPMD-lockstep contract, cache-state-independent."""
    path, dhash = _dict_file(tmp_path, WORDS[:1000])
    cache = DictCache(os.path.join(str(tmp_path), "dc"))
    bs = 128
    _collect([(path, dhash)], cache, bs=bs)   # populate (nproc=1 tee)
    rd = cache.reader(dhash)
    for pid in (0, 1):
        cold = list(frame_blocks(iter(WORDS[:1000]), bs, nproc=2, pid=pid))
        warm = list(frame_packed(rd.chunks(0), rd.total_words, bs,
                                 nproc=2, pid=pid))
        assert len(cold) == len(warm)
        for cb, wb in zip(cold, warm):
            assert (cb.offset, cb.count, cb.padded) == \
                (wb.offset, wb.count, wb.padded)
            rows, lens, nv = wb.prep.materialize()
            packed = pack_candidates_fast(cb.words, 8, 63, capacity=bs)
            assert nv == packed[2]
            assert np.array_equal(np.asarray(packed[0]), rows)


# ---------------------------------------------------------------------------
# engine warm path: resume/found-list equivalence
# ---------------------------------------------------------------------------

PSK = b"dcache-psk-42"
ESSID = b"DictCacheNet"


def _crack_via_source(engine, units, cache, skip=0):
    consumed = []
    src = DictFeedSource(units, batch_size=engine.batch_size,
                         cache=cache, skip=skip)
    feed = CandidateFeed(None, batch_size=engine.batch_size, frames=src,
                         producers=1, prepack=engine.host_packer(),
                         registry=MetricsRegistry())
    try:
        founds = engine.crack_blocks(
            feed, on_batch=lambda c, f: consumed.append(c))
    finally:
        feed.close()
    return founds, consumed


@needs_native
def test_engine_warm_run_equals_cold_run(tmp_path):
    """The acceptance property: found list AND consumed counts from a
    warm unit are identical to the cold unit it replaced — with and
    without a resume skip."""
    words = [b"engine-%04d-word" % i for i in range(100)] + [PSK]
    path, dhash = _dict_file(tmp_path, words)
    units = [(path, dhash)]
    line = synth.make_pmkid_line(PSK, ESSID, seed="dc1")
    cache = DictCache(os.path.join(str(tmp_path), "dc"))
    for skip in (0, 37):
        cold = _crack_via_source(M22000Engine([line], batch_size=32),
                                 units, None, skip=skip)
        got = _crack_via_source(M22000Engine([line], batch_size=32),
                                units, cache, skip=skip)
        assert [f.psk for f in got[0]] == [f.psk for f in cold[0]] == [PSK]
        assert got[1] == cold[1]
        assert sum(got[1]) == len(words) - skip
    # by now the cache is warm: one more pass must be hit-served
    reg = MetricsRegistry()
    cache2 = DictCache(cache.root, registry=reg)
    got = _crack_via_source(M22000Engine([line], batch_size=32),
                            units, cache2)
    assert [f.psk for f in got[0]] == [PSK]
    assert reg.value("dwpa_dictcache_hit_blocks_total") > 0
    assert reg.value("dwpa_dictcache_miss_blocks_total") == 0
