"""Oracle tests anchored on the reference's known-PSK challenge vectors.

The two hashlines below are the client's proof-of-correctness challenge
(help_crack/help_crack.py:692-699): a d-link PMKID and a WPA2 4-way
handshake, both with PSK ``aaaa1234``.  Any cracker backend must crack
both from a one-word dictionary before it may fetch real work.
"""

import pytest

from dwpa_tpu.models import hashline as hl
from dwpa_tpu.oracle import m22000 as oracle

CHALLENGE_PMKID = (
    "WPA*01*8ac36b891edca8eef49094b1afe061ac*1c7ee5e2f2d0*0026c72e4900"
    "*646c696e6b***"
)
CHALLENGE_EAPOL = (
    "WPA*02*269a61ef25e135a4b423832ec4ecc7f4*1c7ee5e2f2d0*0026c72e4900*646c696e6b*"
    "dbd249a3e9cec6ced3360fba3fae9ba4aa6ec6c76105796ff6b5a209d18782ca*"
    "0103007702010a00000000000000000000645b1f684a2566e21266f123abc386"
    "cc576f593e6dc5e3823a32fbd4af929f51000000000000000000000000000000"
    "0000000000000000000000000000000000000000000000000000000000000000"
    "00001830160100000fac020100000fac040100000fac023c000000*00"
)
CHALLENGE_KEY = b"aaaa1234"


def test_parse_challenge_lines():
    p = hl.parse(CHALLENGE_PMKID)
    assert p.hash_type == hl.TYPE_PMKID
    assert p.essid == b"dlink"
    assert p.keyver == 100

    e = hl.parse(CHALLENGE_EAPOL)
    assert e.hash_type == hl.TYPE_EAPOL
    assert e.essid == b"dlink"
    assert e.keyver == 2
    assert len(e.snonce) == 32
    assert e.key_id() != p.key_id()


def test_parse_rejects_garbage():
    for bad in [
        "",
        "WPA*03*aa*bb*cc*dd***",
        CHALLENGE_PMKID.replace("WPA", "WPB"),
        "WPA*01*zz*1c7ee5e2f2d0*0026c72e4900*646c696e6b***",
        "WPA*01*8ac36b891edca8eef49094b1afe061ac*1c7e*0026c72e4900*64***",
    ]:
        with pytest.raises(ValueError):
            hl.parse(bad)


def test_oracle_cracks_challenge_pmkid():
    got = oracle.check_key_m22000(CHALLENGE_PMKID, [b"wrong123", CHALLENGE_KEY])
    assert got is not None
    psk, nc, endian, pmk = got
    assert psk == CHALLENGE_KEY and nc is None and endian is None
    assert pmk == oracle.pmk_from_psk(CHALLENGE_KEY, b"dlink")


def test_oracle_cracks_challenge_eapol():
    # The challenge handshake itself carries a drifted AP nonce: the MIC
    # only verifies with nonce-error-correction +4 little-endian — a nice
    # built-in NC regression vector.
    got = oracle.check_key_m22000(CHALLENGE_EAPOL, [CHALLENGE_KEY])
    assert got is not None
    psk, nc, endian, pmk = got
    assert psk == CHALLENGE_KEY and nc == 4 and endian == "LE"


def test_oracle_rejects_wrong_keys():
    assert oracle.check_key_m22000(CHALLENGE_PMKID, [b"bbbb1234", None]) is None
    assert oracle.check_key_m22000(CHALLENGE_EAPOL, [b"bbbb1234"]) is None


def test_oracle_hex_notation():
    got = oracle.check_key_m22000(CHALLENGE_PMKID, ["$HEX[6161616131323334]"])
    assert got is not None and got[0] == CHALLENGE_KEY


def test_oracle_pmk_reuse_skips_pbkdf2():
    pmk = oracle.pmk_from_psk(CHALLENGE_KEY, b"dlink")
    got = oracle.check_key_m22000(CHALLENGE_PMKID, [b""], pmk=pmk)
    assert got is not None and got[3] == pmk
    got = oracle.check_key_m22000(CHALLENGE_EAPOL, [b""], pmk=pmk)
    assert got is not None and got[1] == 4


def _clean_anonce() -> bytes:
    """The challenge anonce with its true +4 LE drift applied, so the MIC
    verifies with no correction."""
    import struct

    h = hl.parse(CHALLENGE_EAPOL)
    last = struct.unpack_from("<I", h.anonce, 28)[0]
    return h.anonce[:28] + struct.pack("<I", (last + 4) & 0xFFFFFFFF)


def _perturbed_eapol(delta: int, endian: str) -> str:
    """Rebuild the challenge EAPOL line with a perturbed AP nonce.

    If the stored anonce drifted by ``-delta`` relative to the one the
    PTK was computed with, the verifier must recover it at ``+delta``.
    """
    import struct

    h = hl.parse(CHALLENGE_EAPOL)
    clean = _clean_anonce()
    fmt = "<I" if endian == "LE" else ">I"
    last = struct.unpack_from(fmt, clean, 28)[0]
    bad = clean[:28] + struct.pack(fmt, (last - delta) & 0xFFFFFFFF)
    return hl.serialize(
        hl.TYPE_EAPOL, h.pmkid_or_mic, h.mac_ap, h.mac_sta, h.essid,
        bad, h.eapol, h.message_pair,
    )


def test_oracle_exact_after_drift_repair():
    h = hl.parse(CHALLENGE_EAPOL)
    line = hl.serialize(
        hl.TYPE_EAPOL, h.pmkid_or_mic, h.mac_ap, h.mac_sta, h.essid,
        _clean_anonce(), h.eapol, h.message_pair,
    )
    got = oracle.check_key_m22000(line, [CHALLENGE_KEY])
    assert got is not None and got[1] == 0 and got[2] is None


@pytest.mark.parametrize("endian", ["LE", "BE"])
@pytest.mark.parametrize("delta", [1, 3, 8])
def test_oracle_nonce_error_correction(delta, endian):
    line = _perturbed_eapol(delta, endian)
    got = oracle.check_key_m22000(line, [CHALLENGE_KEY], nc=32)
    assert got is not None
    psk, nc, got_endian, _ = got
    assert psk == CHALLENGE_KEY
    assert nc == delta
    # NB: when the last 4 bytes make a palindromic-ish pattern both endians
    # can match; the reference returns whichever the search order hits first.
    assert got_endian in (endian, "LE", "BE")


def test_oracle_nc_budget_respected():
    line = _perturbed_eapol(10, "BE")
    assert oracle.check_key_m22000(line, [CHALLENGE_KEY], nc=8) is None
    assert oracle.check_key_m22000(line, [CHALLENGE_KEY], nc=32) is not None


def _synthetic_line(keyver: int, psk: bytes, essid: bytes) -> str:
    """Forge a handshake for keyver 1/3 coverage using the oracle's own
    primitives (primitives are independently KAT-tested in test_ops)."""
    import struct

    mac_ap = bytes.fromhex("020000000001")
    mac_sta = bytes.fromhex("040000000002")
    anonce = bytes(range(32))
    snonce = bytes(range(64, 96))
    key_info = {1: 0x0109, 3: 0x010B}[keyver]
    eapol = bytearray(121)
    eapol[0:2] = b"\x02\x03"
    struct.pack_into(">H", eapol, 2, 117)
    eapol[4] = 254 if keyver == 1 else 2
    struct.pack_into(">H", eapol, 5, key_info)
    eapol[17:49] = snonce
    eapol = bytes(eapol)

    pmk = oracle.pmk_from_psk(psk, essid)
    h_tmp = hl.parse(
        hl.serialize(hl.TYPE_EAPOL, b"\x00" * 16, mac_ap, mac_sta, essid,
                     anonce, eapol, 0)
    )
    m, n, _ = oracle.nonce_pairs(h_tmp)
    mic = oracle.compute_mic(pmk, keyver, m, n, eapol)
    return hl.serialize(hl.TYPE_EAPOL, mic, mac_ap, mac_sta, essid,
                        anonce, eapol, 0)


@pytest.mark.parametrize("keyver", [1, 3])
def test_oracle_keyver_1_and_3(keyver):
    line = _synthetic_line(keyver, b"superpass", b"testnet")
    got = oracle.check_key_m22000(line, [b"nope nope", b"superpass"])
    assert got is not None and got[0] == b"superpass"
    assert oracle.check_key_m22000(line, [b"wrongpass"]) is None
