"""Deployment-data vendor keygen packs (gen/vendor_data.py).

All constants in these packs are SYNTHETIC — the pack mechanism is the
capability under test (the routerkeygen data-pack equivalent); real ISP
tables are deployment data (see the PARITY.md family classification).
"""

import hashlib
import json

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.gen import vendors as V
from dwpa_tpu.gen.vendor_data import load_vendor_pack
from dwpa_tpu.server import Database, ServerCore
from dwpa_tpu.server.jobs import keygen_precompute

BSSID = bytes.fromhex("0011AA22BB33")


def _one(pack_entry, ssid, bssid=BSSID):
    fams = load_vendor_pack({"families": [pack_entry]})
    return list(fams[0](bssid, ssid))


def test_fixed_family():
    got = _one({"name": "SynthFixed", "ssid_re": r"^SynthNet",
                "kind": "fixed", "keys": ["synthkey01", "synthkey02"]},
               b"SynthNet-7")
    assert got == [("SynthFixed", b"synthkey01"),
                   ("SynthFixed", b"synthkey02")]
    # non-matching SSID: silent, no candidates
    assert _one({"name": "SynthFixed", "ssid_re": r"^SynthNet",
                 "kind": "fixed", "keys": ["synthkey01"]}, b"Other") == []


def test_mac_map_family():
    got = _one({"name": "SynthMac", "ssid_re": r"^MacNet",
                "kind": "mac_map", "slices": [[4, 12]], "case": "upper",
                "prefix": "PP", "offsets": [0, 1]}, b"MacNet_33")
    assert got[0] == ("SynthMac", b"PP" + BSSID.hex().upper()[4:].encode())
    nxt = (int.from_bytes(BSSID, "big") + 1).to_bytes(6, "big")
    assert got[1] == ("SynthMac", b"PP" + nxt.hex().upper()[4:].encode())


def test_hash_map_family_hex_and_charset():
    # hex rendering over literal + MAC-string + SSID group
    entry = {"name": "SynthHash", "ssid_re": r"^HashNet-(\d+)$",
             "kind": "hash_map", "hash": "md5",
             "input": ["seedX", "@MAC", "@ssid_group1"],
             "take": 10, "charset": "hex"}
    got = _one(entry, b"HashNet-42")
    exp = hashlib.md5(b"seedX" + BSSID.hex().upper().encode() + b"42")
    assert got == [("SynthHash", exp.hexdigest()[:10].encode())]

    # alphabet rendering over a binary magic + raw MAC bytes, with skip
    entry2 = {"name": "SynthAlpha", "ssid_re": r"^AlphaNet",
              "kind": "hash_map", "hash": "sha256",
              "input": ["hex:c0ffee", "@mac_bytes"],
              "skip": 2, "take": 8, "charset": "abcdefgh"}
    got2 = _one(entry2, b"AlphaNet")
    d = hashlib.sha256(bytes.fromhex("c0ffee") + BSSID).digest()[2:]
    exp2 = "".join("abcdefgh"[b % 8] for b in d[:8]).encode()
    assert got2 == [("SynthAlpha", exp2)]


def test_hash_map_group_bits_rendering():
    """5-bit-group base-32 rendering (the Fastweb-style bitstream
    archetype): groups are consumed MSB-first across byte boundaries."""
    alpha = "0123456789abcdefghijklmnopqrstuv"  # 32 chars
    entry = {"name": "SynthBits", "ssid_re": r"^BitsNet",
             "kind": "hash_map", "hash": "md5",
             "input": ["bitseed", "@mac_bytes"],
             "take": 10, "charset": alpha, "group_bits": 5}
    got = _one(entry, b"BitsNet")
    digest = hashlib.md5(b"bitseed" + BSSID).digest()
    stream = int.from_bytes(digest, "big")
    exp = "".join(
        alpha[(stream >> (128 - 5 * (i + 1))) & 31] for i in range(10)
    ).encode()
    assert got == [("SynthBits", exp)]


def test_serial_hash_family_with_magic_override():
    entry = {"name": "SynthAGPF", "ssid_re": r"^SerNet-(\d{8})$",
             "kind": "serial_hash",
             "series": {"96": [{"sn": "55501", "q": 0, "k": 1}]},
             "magic_hex": "aa" * 32, "charset": "0123456789", "take": 12}
    got = _one(entry, b"SerNet-96001234")
    assert len(got) == 3  # BSSID neighbourhood 0/+1/-1
    exp = V.alice_agpf_key("55501X%07d" % 96001234, BSSID,
                           magic=b"\xaa" * 32, charset="0123456789",
                           take=12)
    assert got[0] == ("SynthAGPF", exp) and len(exp) == 12


def test_pack_validation_rejects_bad_kind():
    with pytest.raises(ValueError, match="unknown vendor-pack kind"):
        load_vendor_pack({"families": [
            {"name": "x", "ssid_re": ".", "kind": "nope"}]})
    with pytest.raises(KeyError):  # missing required field fails at load
        load_vendor_pack({"families": [
            {"name": "x", "ssid_re": "^V", "kind": "mac_map"}]})


def test_fixed_keys_type_checked_at_load():
    """Non-string / empty fixed keys fail at load (a JSON number would
    TypeError on .encode() on the first matching net mid-cron)."""
    for keys in ([123], [None], [["nested"]], ["ok", ""], [], "notalist"):
        with pytest.raises(ValueError, match="fixed"):
            load_vendor_pack({"families": [
                {"name": "f", "ssid_re": "^F", "kind": "fixed",
                 "keys": keys}]})
    # the valid shape still loads
    assert load_vendor_pack({"families": [
        {"name": "f", "ssid_re": "^F", "kind": "fixed", "keys": ["k1"]}]})


def test_serial_hash_ssid_re_group_validated_at_load():
    """serial_hash feeds m.group(1) to the serial scheme, so the regex
    must guarantee exactly one mandatory capture group — an optional or
    alternated group would match with group(1) = None and raise
    AttributeError mid-cron instead of a clear load error."""
    series = {"96": [{"sn": "55501", "q": 0, "k": 1}]}
    bad_patterns = [
        r"^SerNet-\d{8}$",              # no group at all
        r"^SerNet-(\d{4})(\d{4})$",     # two groups
        r"^SerNet-(\d{8})?$",           # optional: group may be None
        r"^SerNet-(\d{8})*x$",          # star repeat: may be None
        r"^(?:A(\d{8})|B\d{8})$",       # group absent in one branch
    ]
    for pat in bad_patterns:
        with pytest.raises(ValueError, match="mandatory capture group"):
            load_vendor_pack({"families": [
                {"name": "s", "ssid_re": pat, "kind": "serial_hash",
                 "series": series}]})
    # mandatory-group shapes still load: plain, and under a +-repeat
    # (min >= 1 guarantees participation)
    for pat in (r"^SerNet-(\d{8})$", r"^S(?:erNet-(\d{8}))+$"):
        assert load_vendor_pack({"families": [
            {"name": "s", "ssid_re": pat, "kind": "serial_hash",
             "series": series}]})


def test_pack_validation_checks_data_at_load():
    """Value errors must surface at load — not on the first matching net
    mid-cron (the jobs loop would retry the failing tick forever)."""
    bad = [
        {"name": "h", "ssid_re": "^A", "kind": "hash_map",
         "hash": "sha512", "input": ["x"], "take": 4},     # unknown hash
        {"name": "h", "ssid_re": "^A", "kind": "hash_map",
         "input": ["hex:zz"], "take": 4},                  # bad hex magic
        {"name": "h", "ssid_re": "^A", "kind": "hash_map",
         "input": ["@ssid_group2"], "take": 4},            # no such group
        {"name": "h", "ssid_re": "^A", "kind": "hash_map",
         "input": ["@nonsense"], "take": 4},               # unknown token
        {"name": "m", "ssid_re": "^A", "kind": "mac_map",
         "slices": [[4, 99]]},                             # slice range
        {"name": "s", "ssid_re": "^A", "kind": "serial_hash",
         "series": {}, "magic_hex": "xyz"},                # bad magic_hex
    ]
    for entry in bad:
        with pytest.raises((ValueError, KeyError)):
            load_vendor_pack({"families": [entry]})


def test_pack_file_load_and_precompute_end_to_end(tmp_path):
    """A file pack flows through the server CLI seam: keygen precompute
    cracks a net whose PSK only a pack family generates, records the
    pack's algo label, and the rkg log carries the candidates."""
    db = Database(":memory:")
    core = ServerCore(db, dictdir=str(tmp_path / "d"),
                      capdir=str(tmp_path / "c"))
    pack = {"families": [{
        "name": "SynthPack", "ssid_re": r"^PackNet",
        "kind": "hash_map", "hash": "sha1",
        "input": ["packseed", "@mac"], "take": 12, "charset": "hex"}]}
    path = tmp_path / "pack.json"
    path.write_text(json.dumps(pack))
    fams = load_vendor_pack(str(path))

    psk = hashlib.sha1(
        b"packseed" + BSSID.hex().encode()).hexdigest()[:12].encode()
    line = tfx.make_pmkid_line(psk, b"PackNet_1", seed="vdp", mac_ap=BSSID)
    core.add_hashlines([line])
    stats = keygen_precompute(
        core, extra_generators=[V.vendor_candidates] + fams)
    assert stats["cracked"] == 1
    row = core.db.q1("SELECT * FROM nets")
    assert row["n_state"] == 1 and row["pass"] == psk
    assert row["algo"] == "SynthPack"
    assert core.db.q1(
        "SELECT COUNT(*) c FROM rkg WHERE algo = 'SynthPack'")["c"] >= 1
