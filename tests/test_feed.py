"""dwpa_tpu.feed: framing determinism (the resume/lockstep contracts),
producer/consumer pipelining, fault-with-offset delivery, double-buffered
staging, and the engine/client integration of the candidate feed.

The framing tests pin the EXACT ``(mine, global_count)`` sequences of
the former ``client.main.shard_word_blocks`` (which now delegates to
``feed.framing``): resume skip-by-count and the SPMD-lockstep batch
shapes both hang off that framing, so it is compared against a naive
reference implementation across ragged geometries, not just spot
values.
"""

import itertools
import threading
import time

import pytest

import jax

from dwpa_tpu import testing as tfx
from dwpa_tpu.feed import Block, CandidateFeed, DeviceStager, FeedError
from dwpa_tpu.feed.framing import frame_blocks, skip_stream
from dwpa_tpu.models.m22000 import M22000Engine
from dwpa_tpu.obs import MetricsRegistry


def _legacy_shard_word_blocks(words, nproc, pid, batch_size, pad_word=b""):
    """The pre-feed client slicer, verbatim — the reference the framing
    must reproduce exactly (it materialized batch_size * nproc words per
    block on EVERY host, which is what the feed framing fixes)."""
    words = iter(words)
    while True:
        block = list(itertools.islice(words, batch_size * nproc))
        if not block:
            return
        blk = min(batch_size, -(-len(block) // nproc))
        mine = block[pid * blk:(pid + 1) * blk]
        mine += [pad_word] * (blk - len(mine))
        yield mine, len(block)


def _feed_threads():
    return [t for t in threading.enumerate() if t.name.startswith("dwpa-feed")]


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def test_framing_identical_to_legacy_slicer():
    """Satellite: each host materializes only its shard slice, but the
    emitted (mine, global_count) sequences are IDENTICAL to the old
    list(islice(...)) slicer — across full blocks, ragged tails, empty
    shards and degenerate stream lengths."""
    for n in (0, 1, 2, 5, 16, 47, 96, 97, 191, 200):
        words = [b"w%05d" % i for i in range(n)]
        for nproc in (1, 2, 3, 5):
            for pid in range(nproc):
                for bs in (4, 16):
                    got = [(b.words, b.count)
                           for b in frame_blocks(iter(words), bs,
                                                 nproc=nproc, pid=pid)]
                    ref = list(_legacy_shard_word_blocks(
                        iter(words), nproc, pid, bs))
                    assert got == ref, (n, nproc, pid, bs)


def test_client_shard_word_blocks_delegates():
    """The kept-for-compat client entry point rides the feed framing."""
    from dwpa_tpu.client.main import shard_word_blocks

    words = [b"w%05d" % i for i in range(2 * 3 * 16 + 11)]
    for pid in range(3):
        assert (list(shard_word_blocks(iter(words), 3, pid, 16))
                == list(_legacy_shard_word_blocks(iter(words), 3, pid, 16)))


def test_framing_buffers_only_the_host_slice():
    """The memory fix the delegation exists for: peak buffering stays
    well under the batch_size * nproc words the legacy slicer
    materialized (exactly batch_size for host 0 and for full blocks)."""
    bs, nproc = 64, 4
    words = [b"w%06d" % i for i in range(bs * nproc * 3 + 17)]
    for pid in range(nproc):
        mark = []
        list(frame_blocks(iter(words), bs, nproc=nproc, pid=pid,
                          watermark=mark))
        bound = (pid + 1) * (nproc - pid) * bs / nproc + 1
        assert max(mark) <= bound < bs * nproc, (pid, max(mark), bound)
    # host 0's buffer is exactly one slice
    mark0 = []
    list(frame_blocks(iter(words), bs, nproc=nproc, pid=0, watermark=mark0))
    assert max(mark0) == bs


def test_blocks_carry_global_offsets_and_counts():
    blocks = list(frame_blocks((b"c%04d" % i for i in range(150)), 64,
                               base_offset=1000))
    assert [(b.offset, b.count) for b in blocks] == \
        [(1000, 64), (1064, 64), (1128, 22)]
    assert not any(b.padded for b in blocks)


def test_empty_shard_is_an_all_padding_block(monkeypatch):
    """Satellite: the fake two-process harness — with the jax process
    geometry monkeypatched to a 2-host slice, a global block too short
    to reach host 1 still arrives there as an all-padding framed block
    (the lockstep dispatch ``_padding_prep`` needs), and the offsets
    keep advancing by the GLOBAL count on both hosts."""
    monkeypatch.setattr(jax, "process_count", lambda: 2)

    # global stream: one full block (2*bs) then a 1-word tail block —
    # host 1's slice of the tail is empty
    bs = 8
    words = [b"word%04d" % i for i in range(2 * bs + 1)]
    per_host = {}
    for pid in (0, 1):
        monkeypatch.setattr(jax, "process_index", lambda p=pid: p)
        feed = CandidateFeed(iter(words), batch_size=bs, producers=0,
                             registry=MetricsRegistry())
        per_host[pid] = list(feed)
        feed.close()
    # both hosts: same block count, same (offset, count) framing
    for pid in (0, 1):
        assert [(b.offset, b.count) for b in per_host[pid]] == \
            [(0, 2 * bs), (2 * bs, 1)]
    tail0, tail1 = per_host[0][-1], per_host[1][-1]
    assert tail0.words == [words[-1]] and not tail0.padded
    assert tail1.words == [b""] and tail1.padded  # all-padding, dispatched
    # resume offsets advance by the global count on BOTH hosts
    assert tail1.offset + tail1.count == len(words)


def test_skip_stream_counts_short_streams():
    assert skip_stream(iter(range(10)), 4) == 4
    assert skip_stream(iter(range(3)), 10) == 3
    assert skip_stream(iter(range(3)), 0) == 0


# ---------------------------------------------------------------------------
# the feed pipeline
# ---------------------------------------------------------------------------


def test_feed_delivers_in_order_with_telemetry():
    reg = MetricsRegistry()
    n = 1000
    feed = CandidateFeed((b"c%06d" % i for i in range(n)), batch_size=64,
                         registry=reg, name="t1")
    blocks = list(feed)
    feed.close()
    assert [b.offset for b in blocks] == [i * 64 for i in range(len(blocks))]
    assert sum(b.count for b in blocks) == n
    assert [w for b in blocks for w in b.words] == \
        [b"c%06d" % i for i in range(n)]
    # telemetry contract: the documented dwpa_feed_* names are live
    assert reg.value("dwpa_feed_blocks_total", feed="t1") == len(blocks)
    assert reg.value("dwpa_feed_candidates_total", feed="t1") == n
    assert reg.value("dwpa_feed_bytes_total", feed="t1") == 7 * n
    assert reg.value("dwpa_feed_queue_depth", feed="t1") is not None
    # starve histogram: one observation per consumed block
    assert reg.value("dwpa_feed_consumer_starve_seconds",
                     feed="t1") == len(blocks)
    # producer work landed in feed: spans
    assert reg.value("dwpa_span_seconds", span="feed:produce") == len(blocks)
    assert not _feed_threads()


def test_feed_backpressure_bounds_source_consumption():
    """A slow consumer must not let producers run away with the source:
    at most depth blocks are framed ahead of the consumer."""
    pulled = [0]

    def src():
        for i in range(100 * 16):
            pulled[0] += 1
            yield b"w%06d" % i

    feed = CandidateFeed(src(), batch_size=16, depth=2, producers=1,
                         registry=MetricsRegistry())
    taken = 0
    try:
        for _ in feed:
            taken += 1
            time.sleep(0.01)  # slow consumer
            # frames in flight <= depth; +1 block may be mid-framing
            assert pulled[0] <= (taken + 2 + 1) * 16
            if taken >= 6:
                break
    finally:
        feed.close()
    assert not _feed_threads()


def test_producer_fault_reraised_with_offset():
    def faulty():
        for i in range(200):
            if i == 150:
                raise ValueError("disk on fire")
            yield b"x%06d" % i

    feed = CandidateFeed(faulty(), batch_size=64, registry=MetricsRegistry())
    got = []
    with pytest.raises(FeedError) as e:
        for b in feed:
            got.append(b)
    feed.close()
    # two whole blocks delivered; the fault carries the failing block's
    # global offset and chains the original exception
    assert [b.offset for b in got] == [0, 64]
    assert e.value.offset == 128
    assert isinstance(e.value.__cause__, ValueError)
    assert "offset 128" in str(e.value)
    assert not _feed_threads()


def test_inline_mode_runs_without_threads():
    before = set(threading.enumerate())
    feed = CandidateFeed((b"c%05d" % i for i in range(130)), batch_size=64,
                         producers=0, skip=10, registry=MetricsRegistry())
    assert feed.skipped == 10  # eager in inline mode
    blocks = list(feed)
    feed.close()
    assert set(threading.enumerate()) == before
    assert [(b.offset, b.count) for b in blocks] == [(10, 64), (74, 56)]
    # inline faults keep the offset contract
    def faulty():
        yield b"ok-000001"
        raise OSError("gone")

    feed = CandidateFeed(faulty(), batch_size=4, producers=0,
                         registry=MetricsRegistry())
    with pytest.raises(FeedError) as e:
        list(feed)
    assert e.value.offset == 0 and isinstance(e.value.__cause__, OSError)


def test_skip_fast_forward_and_words_view():
    n = 100
    feed = CandidateFeed((b"c%05d" % i for i in range(n)), batch_size=16,
                         skip=30, registry=MetricsRegistry())
    words = list(feed.words())
    feed.close()
    assert feed.skipped == 30
    assert words == [b"c%05d" % i for i in range(30, n)]
    # skip beyond the stream: everything consumed, nothing framed
    feed = CandidateFeed((b"c%05d" % i for i in range(5)), batch_size=16,
                         skip=30, registry=MetricsRegistry())
    assert list(feed) == []
    assert feed.skipped == 5
    feed.close()


def test_close_is_idempotent_and_unblocks_producers():
    feed = CandidateFeed((b"w%07d" % i for i in range(10 ** 6)),
                         batch_size=64, depth=2,
                         registry=MetricsRegistry())
    next(iter(feed))  # producers are live and backpressured
    feed.close()
    feed.close()
    assert not _feed_threads()


# ---------------------------------------------------------------------------
# staging + engine integration
# ---------------------------------------------------------------------------


def test_device_stager_stages_one_block_ahead():
    staged = []

    class FakeEngine:
        def _prepare_block(self, blk):
            staged.append(blk.offset)
            return ("prep", blk.offset)

    blocks = [Block(offset=i * 4, count=4, words=[b"w"] * 4)
              for i in range(3)]
    out = []
    for blk, prep in DeviceStager(FakeEngine(), iter(blocks)):
        # when block N is handed over, N+1's H2D is already enqueued
        assert staged[:len(out) + 2] == [b.offset
                                         for b in blocks[:len(out) + 2]]
        assert prep == ("prep", blk.offset)
        out.append(blk.offset)
    assert out == [0, 4, 8] and staged == [0, 4, 8]


def test_crack_blocks_finds_psk_and_reports_global_counts():
    psk = b"feed-psk-01"
    eng = M22000Engine([tfx.make_pmkid_line(psk, b"FeedNet", seed="cb1")],
                       batch_size=64)
    words = [b"no-%06d" % i for i in range(150)] + [psk]
    reg = MetricsRegistry()
    feed = CandidateFeed(iter(words), batch_size=64,
                         prepack=eng.host_packer(), registry=reg, name="cb")
    reports = []
    founds = eng.crack_blocks(
        feed, on_batch=lambda c, f: reports.append(c))
    feed.close()
    assert [f.psk for f in founds] == [psk]
    # stream-order accounting: cumulative consumed == block offsets+counts
    assert reports == [64, 64, 23]
    assert sum(reports) == len(words)


def test_crack_blocks_prepacked_matches_unpacked():
    """The producer-side native prepack must be an optimization, never a
    semantic change: same founds with and without it (and with the $HEX
    decode exercised through both paths)."""
    psk = b"prepack-psk7"
    words = ([b"chaff-%05d" % i for i in range(40)]
             + [b"$HEX[" + psk.hex().encode() + b"]"]
             + [b"x", b"tail-%05d" % 1])  # b"x" is length-filtered
    founds = {}
    for label, prepack in (("packed", True), ("plain", False)):
        eng = M22000Engine(
            [tfx.make_pmkid_line(psk, b"PrepackNet", seed="pp1")],
            batch_size=16)
        feed = CandidateFeed(
            iter(words), batch_size=16,
            prepack=eng.host_packer() if prepack else None,
            registry=MetricsRegistry())
        founds[label] = [f.psk for f in eng.crack_blocks(feed)]
        feed.close()
    assert founds["packed"] == founds["plain"] == [psk]


def test_crack_blocks_skips_invalid_block_but_reports_count():
    """A block with zero valid words (single-process) is not dispatched
    but its count still reaches on_batch — the resume contract."""
    eng = M22000Engine(
        [tfx.make_pmkid_line(b"skipblk-psk", b"SkipNet", seed="sb1")],
        batch_size=16)
    words = [b"x"] * 16 + [b"valid-%05d" % i for i in range(16)]
    feed = CandidateFeed(iter(words), batch_size=16,
                         prepack=eng.host_packer(),
                         registry=MetricsRegistry())
    reports = []
    eng.crack_blocks(feed, on_batch=lambda c, f: reports.append(c))
    feed.close()
    assert reports == [16, 16]


def test_stage_times_prepare_is_residual_with_prepack():
    """Satellite: with producer-side packing, the engine's "prepare"
    accumulator measures only the on-thread staging residual — the keys
    survive (API compat) but pack time lives in the feed's spans."""
    eng = M22000Engine(
        [tfx.make_pmkid_line(b"residual-psk", b"ResNet", seed="st1")],
        batch_size=64)
    assert set(eng.stage_times) == {"prepare", "dispatch", "collect"}
    reg = MetricsRegistry()
    feed = CandidateFeed((b"w%06d" % i for i in range(64 * 4)),
                         batch_size=64, prepack=eng.host_packer(),
                         registry=reg, name="res")
    eng.crack_blocks(feed)
    feed.close()
    # producer pack time was recorded to the feed span, not "prepare"
    assert reg.value("dwpa_span_seconds", span="feed:produce") == 4
    assert eng.stage_times["prepare"] < eng.stage_times["collect"] + \
        eng.stage_times["dispatch"] + 10  # smoke: keys populated, finite
