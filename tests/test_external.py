"""Live-adapter tests: wigle / 3wifi / reCAPTCHA / MX against a local
stub HTTP server speaking the canned wire shapes of the real services
(wigle.php:30-53, 3wifi.php:27-66, index.php:16-35, common.php:981-992).

The adapters' seams (jobs.geolocate / jobs.psk_lookup / core.captcha /
core.email_check) are exercised end-to-end — including through the jobs
CLI — so a deployment flipping on ``--wigle-api`` runs the exact code
path tested here, just with the default endpoint URLs.
"""

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.server import Database, ServerCore
from dwpa_tpu.server.db import long2mac, mac2long
from dwpa_tpu.server.external import (
    RecaptchaVerifier,
    ThreeWifiClient,
    WigleClient,
    mx_email_validator,
)
from dwpa_tpu.server.jobs import geolocate, psk_lookup

PSK = b"stub-battery-1"
ESSID = b"StubNet"


class _StubHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _reply(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv = self.server
        srv.requests.append({
            "path": self.path,
            "headers": dict(self.headers),
            "body": b"",
        })
        route = self.path.split("?")[0]
        self._reply(*srv.routes.get(route, ({"error": "no route"}, 404)))

    def do_POST(self):
        srv = self.server
        n = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(n)
        srv.requests.append({
            "path": self.path,
            "headers": dict(self.headers),
            "body": body,
        })
        route = self.path.split("?")[0]
        self._reply(*srv.routes.get(route, ({"error": "no route"}, 404)))


@pytest.fixture
def stub():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _StubHandler)
    srv.routes = {}     # path -> (json_obj, status)
    srv.requests = []   # recorded request dicts
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    srv.url = f"http://127.0.0.1:{srv.server_address[1]}"
    yield srv
    srv.shutdown()
    srv.server_close()


@pytest.fixture
def core(tmp_path):
    return ServerCore(Database(":memory:"), dictdir=str(tmp_path / "d"),
                      capdir=str(tmp_path / "c"))


def _plant_net(core, psk=PSK, essid=ESSID, seed="stub-seed"):
    line = tfx.make_pmkid_line(psk, essid, seed=seed)
    core.add_hashlines([line])
    row = core.db.q1("SELECT bssid FROM nets")
    return long2mac(row["bssid"])


# -- wigle ----------------------------------------------------------------


def test_wigle_geolocate_end_to_end(core, stub):
    mac = _plant_net(core)
    stub.routes["/search"] = ({
        "success": True, "resultCount": 1,
        "results": [{"trilat": 42.5, "trilong": -71.1, "country": "US",
                     "region": "MA", "city": "Cambridge"}],
    }, 200)
    sleeps = []
    cli = WigleClient("QWxhZGRpbjpvcGVu", url=stub.url + "/search",
                      sleep=sleeps.append)
    assert geolocate(core, cli) == 1
    row = core.db.q1("SELECT lat, lon, country, region, city, flags "
                     "FROM bssids")
    assert (row["lat"], row["lon"]) == (42.5, -71.1)
    assert (row["country"], row["region"], row["city"]) == \
        ("US", "MA", "Cambridge")
    assert row["flags"] & 2
    req = stub.requests[0]
    assert req["headers"]["Authorization"] == "Basic QWxhZGRpbjpvcGVu"
    assert req["headers"]["User-Agent"] == "wpa-sec"
    netid = urllib.parse.parse_qs(req["path"].split("?")[1])["netid"][0]
    assert netid == ":".join("%02x" % b for b in mac)


def test_wigle_ambiguous_answer_marks_attempted(core, stub):
    """A parsed, successful response with resultCount != 1 is a
    definitive 'not found': the row is stamped attempted (flags|2) with
    no location, exactly like wigle.php:43-49."""
    _plant_net(core)
    stub.routes["/search"] = ({"success": True, "resultCount": 3,
                               "results": [{}, {}, {}]}, 200)
    cli = WigleClient("k", url=stub.url + "/search", sleep=lambda s: None)
    assert cli(b"\xaa\xbb\xcc\xdd\xee\xff") is None
    assert geolocate(core, cli) == 1
    row = core.db.q1("SELECT lat, flags FROM bssids")
    assert row["lat"] is None and row["flags"] & 2


def test_wigle_outage_leaves_rows_unmarked(core, stub):
    """Transport errors and service refusals must NOT burn the row's
    one geolocation attempt — the reference writes nothing on a failed
    request, so the BSSID is retried next cron tick."""
    from dwpa_tpu.server.jobs import LookupUnavailable

    _plant_net(core)
    cli = WigleClient("k", url=stub.url + "/search", sleep=lambda s: None)
    stub.routes["/search"] = ({"oops": 1}, 500)
    with pytest.raises(LookupUnavailable):
        cli(b"\xaa\xbb\xcc\xdd\xee\xff")
    stub.routes["/search"] = ({"success": False, "message": "quota"}, 200)
    with pytest.raises(LookupUnavailable):
        cli(b"\xaa\xbb\xcc\xdd\xee\xff")
    assert geolocate(core, cli) == 0
    assert core.db.q1("SELECT flags FROM bssids")["flags"] & 2 == 0
    # 3wifi path: outage abandons the batch without flags|1 marking
    tw = ThreeWifiClient("k", url=stub.url + "/apiquery")
    stub.routes["/apiquery"] = ({"result": False}, 200)
    rep = psk_lookup(core, tw)
    assert rep == {"queried": 0, "submitted": 0, "unavailable": True}
    assert core.db.q1("SELECT flags FROM bssids")["flags"] & 1 == 0


def test_wigle_throttle_one_rps():
    """Back-to-back queries must sleep out the 1 s interval
    (wigle.php:53); the first query pays nothing."""
    sleeps = []
    clock = iter([0.0, 0.3, 1.3]).__next__
    cli = WigleClient("k", url="http://127.0.0.1:9/none",
                      sleep=sleeps.append, opener=None)
    cli.throttle._clock = clock
    cli.throttle.wait()
    assert sleeps == []
    cli.throttle.wait()
    assert len(sleeps) == 1 and abs(sleeps[0] - 0.7) < 1e-9


# -- 3wifi ----------------------------------------------------------------


def test_3wifi_psk_lookup_end_to_end(core, stub):
    """A 3wifi hit flows through put_work re-verification and cracks the
    net — and a wrong key from the database is rejected (never trusted,
    3wifi.php:66)."""
    mac = _plant_net(core)
    stub.routes["/apiquery"] = ({
        "result": True,
        "data": {mac.hex(): [{"bssid": mac.hex(), "key": PSK.decode()}]},
    }, 200)
    cli = ThreeWifiClient("apikey123", url=stub.url + "/apiquery")
    rep = psk_lookup(core, cli)
    assert rep == {"queried": 1, "submitted": 1}
    row = core.db.q1("SELECT n_state, pass FROM nets")
    assert row["n_state"] == 1 and row["pass"] == PSK
    sent = json.loads(stub.requests[0]["body"])
    assert sent == {"key": "apikey123", "bssid": [mac.hex()]}
    assert core.db.q1("SELECT flags FROM bssids")["flags"] & 1


def test_3wifi_wrong_key_rejected(core, stub):
    mac = _plant_net(core, seed="stub-wrong")
    stub.routes["/apiquery"] = ({
        "result": True,
        "data": {mac.hex(): [{"bssid": mac.hex(), "key": "not-the-psk"}]},
    }, 200)
    cli = ThreeWifiClient("k", url=stub.url + "/apiquery")
    rep = psk_lookup(core, cli)
    assert rep["submitted"] == 1
    assert core.db.q1("SELECT n_state FROM nets")["n_state"] == 0


def test_3wifi_colon_macs_and_garbage_rows(stub):
    stub.routes["/apiquery"] = ({
        "result": True,
        "data": [
            [{"bssid": "AA:BB:CC:DD:EE:FF", "key": "pass1"}],
            [{"bssid": "zz", "key": "x"}],
            [{"nokey": 1}],
            [],
        ],
    }, 200)
    cli = ThreeWifiClient("k", url=stub.url + "/apiquery")
    out = cli([b"\xaa\xbb\xcc\xdd\xee\xff"])
    assert out == {b"\xaa\xbb\xcc\xdd\xee\xff": b"pass1"}


# -- reCAPTCHA ------------------------------------------------------------


def test_recaptcha_verifier(stub):
    stub.routes["/siteverify"] = ({"success": True}, 200)
    v = RecaptchaVerifier("sekrit", url=stub.url + "/siteverify")
    assert v("tok-abc", "9.9.9.9") is True
    form = urllib.parse.parse_qs(stub.requests[0]["body"].decode())
    assert form == {"secret": ["sekrit"], "response": ["tok-abc"],
                    "remoteip": ["9.9.9.9"]}
    stub.routes["/siteverify"] = ({"success": False,
                                   "error-codes": ["timeout"]}, 200)
    assert v("tok-bad", "9.9.9.9") is False
    stub.routes["/siteverify"] = ({"success": True}, 500)
    assert v("tok-err", "9.9.9.9") is False  # transport error -> not verified


def test_recaptcha_gates_key_issue(core, stub):
    """Wired as core.captcha, a failing verification blocks the key-issue
    form exactly like index.php:36-44."""
    import io

    from dwpa_tpu.server import make_wsgi_app

    stub.routes["/siteverify"] = ({"success": False}, 200)
    core.captcha = RecaptchaVerifier("s", url=stub.url + "/siteverify")
    app = make_wsgi_app(core)
    body = b"mail=a%40example.com&g-recaptcha-response=tok"
    out = {}
    environ = {
        "REQUEST_METHOD": "POST", "PATH_INFO": "/", "QUERY_STRING": "get_key",
        "CONTENT_TYPE": "application/x-www-form-urlencoded",
        "CONTENT_LENGTH": str(len(body)), "wsgi.input": io.BytesIO(body),
        "REMOTE_ADDR": "9.9.9.9",
    }
    resp = b"".join(app(environ, lambda s, h: out.update(status=s)))
    assert b"Captcha validation failed" in resp
    assert core.db.q1("SELECT COUNT(*) c FROM users")["c"] == 0


# -- MX validation --------------------------------------------------------


def test_mx_email_validator_seam():
    asked = []

    def resolver(domain):
        asked.append(domain)
        return domain == "has-mx.example"

    check = mx_email_validator(resolver)
    assert check("user@has-mx.example") is True
    assert check("user@no-mx.example") is False
    assert asked == ["has-mx.example", "no-mx.example"]
    # format failures never reach the resolver
    assert check("not-an-email") is False
    assert len(asked) == 2

    def broken(domain):
        raise OSError("resolver down")

    assert mx_email_validator(broken)("user@x.example") is True  # fail-open


# -- CLI end-to-end -------------------------------------------------------


def test_jobs_cli_wigle_api_flag(tmp_path, stub, capsys):
    """`jobs --wigle-api K --wigle-url <stub>` geolocates through the
    live adapter — the VERDICT's '--wigle-api-style config works
    end-to-end against the stub'."""
    from dwpa_tpu.server.__main__ import main

    dbpath = str(tmp_path / "wpa.sqlite")
    core = ServerCore(Database(dbpath), dictdir=str(tmp_path / "d"),
                      capdir=str(tmp_path / "c"))
    _plant_net(core)
    stub.routes["/search"] = ({
        "success": True, "resultCount": 1,
        "results": [{"trilat": 1.5, "trilong": 2.5, "country": "BG",
                     "region": "", "city": "Sofia"}],
    }, 200)
    main(["jobs", "--db", dbpath, "--wigle-api", "k3y",
          "--wigle-url", stub.url + "/search"])
    row = core.db.q1("SELECT lat, lon, city FROM bssids")
    assert (row["lat"], row["lon"], row["city"]) == (1.5, 2.5, "Sofia")
    assert stub.requests[0]["headers"]["Authorization"] == "Basic k3y"


def test_3wifi_numeric_bssid_row_skipped(stub):
    """A malformed row with a non-string bssid is skipped, not a crash
    of the whole lookup batch."""
    stub.routes["/apiquery"] = ({
        "result": True,
        "data": [
            [{"bssid": 112233445566, "key": "p"}],
            [{"bssid": "AA:BB:CC:DD:EE:FF", "key": "good"}],
        ],
    }, 200)
    cli = ThreeWifiClient("k", url=stub.url + "/apiquery")
    assert cli([b"\xaa\xbb\xcc\xdd\xee\xff"]) == \
        {b"\xaa\xbb\xcc\xdd\xee\xff": b"good"}


def test_mx_output_parsing_fails_open():
    """Resolver-output decision: affirmative answers and affirmative
    NXDOMAINs decide; unrecognized tooling output fails open."""
    from dwpa_tpu.server.external import _parse_mx_output

    assert _parse_mx_output("example.com mail exchanger = 10 mx.example.com.")
    assert not _parse_mx_output("** server can't find no-mx.example.: NXDOMAIN")
    assert not _parse_mx_output(";; connection timed out; no servers could be reached")
    # busybox nslookup without -type support: unrecognized -> fail open
    assert _parse_mx_output("nslookup: invalid option -- t\nUsage: nslookup HOST")
    assert _parse_mx_output("")
