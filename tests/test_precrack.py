"""Differential parity suite for the batched server-side pre-crack.

The whole contract of ``server/precrack.py`` is that batching changes
WHERE the PBKDF2 work happens, never WHAT any verdict is: every test
here compares the batched path against the per-candidate oracle (or
against ``keygen_precompute``, the scalar sweep it supersedes) and
demands bit-identical results — on the host path, on the forced-jax
device path, with a store, with a poisoned cache, and across an
injected mid-sweep crash.
"""

import gzip
import logging
import os

import pytest

from dwpa_tpu import testing as tfx
from dwpa_tpu.chaos.dbfault import (DbFaultPlan, SimulatedCrash, install,
                                    sweep_invariants)
from dwpa_tpu.models import hashline as hl
from dwpa_tpu.obs import MetricsRegistry
from dwpa_tpu.oracle import m22000 as oracle
from dwpa_tpu.server import Database, ServerCore
from dwpa_tpu.server.core import SERVER_NC
from dwpa_tpu.server.jobs import keygen_precompute, precrack, regen_rkg_dict
from dwpa_tpu.server.precrack import PmkBatcher, PrecrackEngine, verify_batch

PSK = b"precrack-psk"
ESSID = b"PrecrackLan"


@pytest.fixture
def core(tmp_path):
    db = Database(":memory:")
    return ServerCore(db, dictdir=str(tmp_path / "dicts"),
                      capdir=str(tmp_path / "caps"),
                      registry=MetricsRegistry())


def _single_hit_line(i: int) -> str:
    """A net the Single generator cracks (ssid.lower() + "1")."""
    essid = b"PrecrackNet%02d" % i
    return tfx.make_eapol_line(essid.lower() + b"1", essid,
                               keyver=2, seed="pc%02d" % i)


# ---------------------------------------------------------------------------
# verify_batch: bit-identity against the per-candidate oracle
# ---------------------------------------------------------------------------


def _mixed_items():
    """Oracle items across keyvers, hash types, $HEX keys, wrong keys,
    multi-key lists and an injected first-key PMK."""
    hexed = b"$HEX[" + PSK.hex().encode() + b"]"
    items = [
        (tfx.make_pmkid_line(PSK, ESSID, seed="vb-p"),
         [b"not-the-psk", PSK], None),
        (tfx.make_eapol_line(PSK, ESSID, keyver=1, seed="vb-1"),
         [PSK], None),
        (tfx.make_eapol_line(PSK, b"OtherLanHere", keyver=2, seed="vb-2"),
         [b"miss-one00", b"miss-two00", PSK], None),
        (tfx.make_eapol_line(PSK, ESSID, keyver=3, seed="vb-3"),
         [hexed], None),
        (tfx.make_eapol_line(PSK, ESSID, keyver=2, nc_delta=3, seed="vb-n"),
         [b"all", b"of-these0", b"are-wrong"], None),
        (tfx.make_eapol_line(PSK, ESSID, keyver=2, seed="vb-i"),
         [PSK, b"never-reached"], oracle.pmk_from_psk(PSK, ESSID)),
        # out-of-range word lengths (the host-only oddball path)
        (tfx.make_pmkid_line(PSK, ESSID, seed="vb-o"),
         [b"short", b"x" * 70, PSK], None),
    ]
    return items


def test_verify_batch_matches_oracle_over_mixed_items():
    items = _mixed_items()
    got = verify_batch(items, nc=SERVER_NC)
    want = [oracle.check_key_m22000(line, keys, pmk=pmk, nc=SERVER_NC)
            for line, keys, pmk in items]
    assert got == want
    # the suite must exercise both hit and miss verdicts to mean much
    assert any(r is not None for r in want)
    assert any(r is None for r in want)


def test_verify_batch_device_path_is_bit_identical():
    """device="on" forces the fused jax kernel even on CPU: verdicts
    (and the PMK element each returns) must not change."""
    items = _mixed_items()
    got = verify_batch(items, nc=SERVER_NC,
                       batcher=PmkBatcher(device="on", batch=8))
    want = [oracle.check_key_m22000(line, keys, pmk=pmk, nc=SERVER_NC)
            for line, keys, pmk in items]
    assert got == want


def test_verify_batch_accepts_parsed_hashlines_and_empty():
    h = hl.parse(tfx.make_pmkid_line(PSK, ESSID, seed="vb-h"))
    assert verify_batch([], nc=SERVER_NC) == []
    got = verify_batch([(h, [PSK], None)], nc=SERVER_NC)
    assert got == [oracle.check_key_m22000(h, [PSK], nc=SERVER_NC)]


def test_batcher_store_roundtrip(tmp_path):
    """Fresh derivations land in the store; a second batcher re-reads
    them (store_hits) and still returns hashlib-exact PMKs."""
    from dwpa_tpu.pmkstore import PMKStore

    pairs = [(b"StoreNetA", b"storeword%02d" % i) for i in range(5)]
    pairs += [(b"StoreNetB", b"storeword%02d" % i) for i in range(3)]
    store = PMKStore(str(tmp_path / "pmks"))
    b1 = PmkBatcher(store=store, device="off")
    s1 = b1.prewarm(pairs)
    assert s1["unique"] == len(pairs) and s1["store_hits"] == 0
    b2 = PmkBatcher(store=store, device="off")
    s2 = b2.prewarm(pairs)
    assert s2["store_hits"] == len(pairs) and s2["derived"] == 0
    for e, w in pairs:
        assert b2.pmk(e, w) == oracle.pmk_from_psk(w, e)


# ---------------------------------------------------------------------------
# PrecrackEngine vs keygen_precompute: the differential sweep
# ---------------------------------------------------------------------------


def _net_rows(core):
    return [(r["net_id"], r["pass"], r["pmk"], r["nc"], r["endian"],
             r["algo"], r["n_state"])
            for r in core.db.q("SELECT * FROM nets ORDER BY net_id")]


def _rkg_rows(core):
    return [(r["net_id"], r["algo"], r["pass"], r["n_state"])
            for r in core.db.q("SELECT * FROM rkg ORDER BY net_id, pass")]


def _ingest_fleet(core):
    lines = [_single_hit_line(i) for i in range(3)]
    # one net no generator cracks (released with algo = '')
    lines.append(tfx.make_eapol_line(b"genuinely-random-psk!", b"NoVendorLan",
                                     keyver=2, seed="pc-miss"))
    core.add_hashlines(lines)


def test_engine_matches_keygen_precompute(tmp_path):
    """The tentpole differential: over the same nets, the fused engine
    (replay/dict sources disabled) must write the exact rows the scalar
    keygen sweep writes — same cracked set, same rkg attempt prefixes,
    same algo release column."""
    a = ServerCore(Database(":memory:"), dictdir=str(tmp_path / "da"),
                   capdir=str(tmp_path / "ca"), registry=MetricsRegistry())
    b = ServerCore(Database(":memory:"), dictdir=str(tmp_path / "db"),
                   capdir=str(tmp_path / "cb"), registry=MetricsRegistry())
    _ingest_fleet(a)
    _ingest_fleet(b)

    ra = keygen_precompute(a)
    eng = PrecrackEngine(b, device="off", dict_limit=0)
    rb = eng.run()
    assert ra["processed"] == rb["processed"] == 4
    assert ra["cracked"] == rb["cracked"] == 3
    assert _net_rows(a) == _net_rows(b)
    assert _rkg_rows(a) == _rkg_rows(b)
    # both regenerated the same vendor-key dictionary
    with open(os.path.join(a.dictdir, "rkg.txt.gz"), "rb") as f:
        da = f.read()
    with open(os.path.join(b.dictdir, "rkg.txt.gz"), "rb") as f:
        db_ = f.read()
    assert da == db_


def test_engine_replay_and_dict_sources(core):
    """The server-only sources: a cracked sibling's PSK replays onto a
    same-ESSID net (its stored PMK seeded — zero extra PBKDF2), and the
    cracked corpus replays as a dictionary onto unrelated nets."""
    secret = b"not-any-vendor-key"
    l1 = tfx.make_eapol_line(secret, ESSID, keyver=2, seed="rp1")
    # same ESSID, different station -> replay source; different ESSID
    # -> only the dict source can reach it
    l2 = tfx.make_eapol_line(secret, ESSID, keyver=2, seed="rp2")
    l3 = tfx.make_pmkid_line(secret, b"UnrelatedLan", seed="rp3")
    core.add_hashlines([l1, l2, l3])

    # crack l1 out-of-band (straight SQL, NOT _try_accept — that would
    # replay onto l2 right here and leave nothing for the engine)
    net = core.db.q1("SELECT net_id FROM nets WHERE ssid = ? "
                     "ORDER BY net_id", (ESSID,))
    core.db.x(
        "UPDATE nets SET pass = ?, pmk = ?, n_state = 1, algo = 'Manual' "
        "WHERE net_id = ?",
        (secret, oracle.pmk_from_psk(secret, ESSID), net["net_id"]))

    eng = PrecrackEngine(core, device="off")
    out = eng.run()
    assert out["processed"] == 2 and out["cracked"] == 2
    rows = core.db.q("SELECT algo, pass, n_state FROM nets "
                     "WHERE algo != 'Manual' ORDER BY net_id")
    assert [(r["algo"], r["pass"], r["n_state"]) for r in rows] == [
        ("Replay", secret, 1), ("Dict", secret, 1)]
    reg = core.registry
    assert reg.value("dwpa_precrack_candidates_total", source="replay") >= 1
    assert reg.value("dwpa_precrack_candidates_total", source="dict") >= 1
    assert reg.value("dwpa_precrack_free_founds_total") == 2


def test_engine_empty_candidate_net(core, monkeypatch):
    """A net with literally zero candidates is still RELEASED (algo '')
    — pre-crack must never wedge a net out of the volunteer queue."""
    import dwpa_tpu.gen.psktool as psktool
    import dwpa_tpu.server.jobs as jobs_mod

    core.add_hashlines([tfx.make_eapol_line(PSK, ESSID, keyver=2,
                                            seed="empty")])
    monkeypatch.setattr(jobs_mod, "single_mode_candidates",
                        lambda bssid, ssid: [])
    monkeypatch.setattr(psktool, "psk_candidates",
                        lambda essid, mac_ap, mac_sta=None: [])
    eng = PrecrackEngine(core, device="off", generators=[], dict_limit=0)
    out = eng.run()
    assert out == {"processed": 1, "cracked": 0, "candidates": 0}
    row = core.db.q1("SELECT algo, n_state FROM nets")
    assert row["algo"] == "" and row["n_state"] == 0
    assert core.db.q1("SELECT COUNT(*) c FROM rkg")["c"] == 0
    # nothing left to process: the next run is a no-op
    assert eng.run() == {"processed": 0, "cracked": 0, "candidates": 0}


def test_poisoned_pmk_is_a_miss_never_an_accept(core):
    """Trust boundary: a wrong PMK planted in the cache can only turn a
    would-be hit into a miss (net stays uncracked, still released); it
    can never manufacture an accept.  Clearing the poison re-cracks."""
    core.add_hashlines([_single_hit_line(7)])
    essid = b"PrecrackNet07"
    right = essid.lower() + b"1"

    eng = PrecrackEngine(core, device="off", dict_limit=0)
    for w in (right, b"some-wrong-word"):
        eng.batcher.seed(essid, w, b"\xee" * 32)
    out = eng.run()
    assert out["cracked"] == 0
    row = core.db.q1("SELECT algo, n_state, pass FROM nets")
    assert row["n_state"] == 0 and row["pass"] is None
    assert row["algo"] == ""  # released despite the poisoned miss

    core.db.x("UPDATE nets SET algo = NULL")
    core.db.x("DELETE FROM rkg")
    clean = PrecrackEngine(core, device="off", dict_limit=0)
    assert clean.run()["cracked"] == 1
    assert core.db.q1("SELECT pass FROM nets")["pass"] == right


def test_mid_sweep_crash_keeps_nets_atomic(tmp_path):
    """Chaos: crash the core at a statement seam inside the LAST net's
    transaction.  Earlier nets stay fully committed, the interrupted net
    stays fully unprocessed (algo NULL, no rkg rows), the invariant
    sweep is clean, and a rerun converges to the exact no-crash state."""

    def build(tag):
        c = ServerCore(Database(":memory:"),
                       dictdir=str(tmp_path / ("d" + tag)),
                       capdir=str(tmp_path / ("c" + tag)),
                       registry=MetricsRegistry())
        c.add_hashlines([_single_hit_line(i) for i in range(2)])
        return c

    # recording pass: the statement stream of a healthy sweep, with the
    # SQL text kept — the fault plan's schedule only logs verbs, and the
    # post-sweep dictionary regen issues inserts of its own AFTER every
    # net has committed, so "last insert" must mean "last rkg insert"
    ref = build("ref")
    stmts = []
    real_exec = ref.db._exec
    ref.db._exec = lambda sql, params=(): (stmts.append(sql),
                                           real_exec(sql, params))[1]
    PrecrackEngine(ref, device="off", dict_limit=0).run()
    ref.db._exec = real_exec
    inserts = [i for i, sql in enumerate(stmts)
               if sql.lstrip().lower().startswith("insert into rkg")]
    assert inserts, "sweep recorded no rkg inserts?"

    # replay pass: crash at the LAST rkg insert — net 1's tx already
    # committed, net 2's tx is open and must vanish wholesale
    vic = build("vic")
    uninstall = install(vic.db, DbFaultPlan(seed=0).force_at(inserts[-1],
                                                            "crash"))
    with pytest.raises(SimulatedCrash):
        PrecrackEngine(vic, device="off", dict_limit=0).run()
    uninstall()
    assert sweep_invariants(vic.db) == []
    rows = vic.db.q("SELECT algo, n_state FROM nets ORDER BY net_id")
    assert rows[0]["algo"] == "Single" and rows[0]["n_state"] == 1
    assert rows[1]["algo"] is None and rows[1]["n_state"] == 0
    assert vic.db.q1(
        "SELECT COUNT(*) c FROM rkg WHERE net_id = ?",
        (vic.db.q("SELECT net_id FROM nets ORDER BY net_id")[1]["net_id"],)
    )["c"] == 0

    # restart: the rerun picks up ONLY the unprocessed net and lands on
    # the healthy end state
    assert PrecrackEngine(vic, device="off",
                          dict_limit=0).run()["cracked"] == 1
    assert sweep_invariants(vic.db) == []
    assert _net_rows(vic) == _net_rows(ref)
    assert _rkg_rows(vic) == _rkg_rows(ref)


def test_engine_skips_nets_cracked_mid_sweep(core):
    """The in-tx re-check: a net accepted between candidate collection
    and its per-net transaction is left alone (no duplicate rkg rows,
    no algo overwrite)."""
    core.add_hashlines([_single_hit_line(9)])
    eng = PrecrackEngine(core, device="off", dict_limit=0)
    net = core.db.q1("SELECT * FROM nets")

    real_prewarm = eng.batcher.prewarm

    def racing_prewarm(pairs):
        # a volunteer submits the right key while the wave derives
        core._try_accept(net, b"precracknet091")
        core.db.x("UPDATE nets SET algo = 'Volunteer' WHERE net_id = ?",
                  (net["net_id"],))
        return real_prewarm(pairs)

    eng.batcher.prewarm = racing_prewarm
    out = eng.run()
    assert out["processed"] == 1 and out["cracked"] == 0
    row = core.db.q1("SELECT algo, n_state FROM nets")
    assert row["algo"] == "Volunteer" and row["n_state"] == 1
    assert core.db.q1("SELECT COUNT(*) c FROM rkg")["c"] == 0


# ---------------------------------------------------------------------------
# ingestion hook + cron wiring
# ---------------------------------------------------------------------------


def test_ingest_hook_precracks_new_nets(core):
    """With an engine wired on the core, a freshly ingested net arrives
    already cracked — no cron tick, no volunteer lease."""
    core.precrack = PrecrackEngine(core, device="off", dict_limit=0)
    report = core.add_hashlines([_single_hit_line(4)])
    assert report["new"] == 1
    row = core.db.q1("SELECT pass, algo, n_state FROM nets")
    assert row["n_state"] == 1 and row["algo"] == "Single"
    assert row["pass"] == b"precracknet041"
    # ingest report shape is unchanged by the hook plumbing
    assert "new_ids" not in report


def test_precrack_job_caches_engine_on_core(core):
    core.add_hashlines([_single_hit_line(5)])
    out = precrack(core, device="off", dict_limit=0)
    assert out["processed"] == 1 and out["cracked"] == 1
    assert isinstance(core.precrack, PrecrackEngine)
    eng = core.precrack
    # second tick reuses the engine (shared memo/store) and is a no-op
    assert precrack(core, device="off", dict_limit=0)["processed"] == 0
    assert core.precrack is eng
    assert core.registry.value("dwpa_span_seconds",
                               span="job:precrack") == 2


# ---------------------------------------------------------------------------
# satellites: keygen batching + rkg dict regeneration skip
# ---------------------------------------------------------------------------


def test_keygen_makes_one_oracle_call_per_net(core, monkeypatch):
    """Satellite: keygen_precompute now hands the oracle each net's
    whole candidate list at once — N nets, N calls — and still records
    the scalar loop's tried-prefix rkg rows."""
    import dwpa_tpu.server.jobs as jobs_mod

    mac = bytes.fromhex("aabbccddeeff")
    # first Single candidate (bssid 12-hex, delta 0) is the PSK: the
    # tried prefix must collapse to exactly one rkg row
    lines = [tfx.make_eapol_line(b"aabbccddeeff", b"FirstCandLan",
                                 keyver=2, seed="kg1", mac_ap=mac),
             _single_hit_line(6)]
    core.add_hashlines(lines)

    calls = []
    real = oracle.check_key_m22000

    def counting(line, keys, **kw):
        calls.append(len(list(keys)))
        return real(line, keys, **kw)

    monkeypatch.setattr(jobs_mod.oracle, "check_key_m22000", counting)
    out = keygen_precompute(core)
    assert out == {"processed": 2, "cracked": 2}
    assert len(calls) == 2          # ONE oracle call per net
    assert all(n > 1 for n in calls)
    first = core.db.q("SELECT * FROM rkg ORDER BY rowid LIMIT 1")[0]
    assert first["pass"] == b"aabbccddeeff"
    assert core.db.q1(
        "SELECT COUNT(*) c FROM rkg WHERE net_id = ?",
        (first["net_id"],))["c"] == 1


def test_regen_rkg_dict_skips_unchanged_rewrite(core, caplog):
    """Satellite: an unchanged cracked-rkg row set skips the gzip -9
    rewrite (content signature in the stats table) and logs the skip;
    a new cracked row invalidates the signature and rewrites."""
    core.add_hashlines([_single_hit_line(1)])
    keygen_precompute(core)
    path = os.path.join(core.dictdir, "rkg.txt.gz")
    with open(path, "rb") as f:
        blob = f.read()
    assert gzip.decompress(blob) == b"precracknet011\n"
    assert core.db.get_stat("rkg_dict_sig") != 0

    # unchanged word set: the sentinel survives = no rewrite happened
    with open(path, "wb") as f:
        f.write(b"sentinel")
    with caplog.at_level(logging.INFO, logger="dwpa_tpu.server.jobs"):
        assert regen_rkg_dict(core, path) == 1
    assert "skipping gzip rewrite" in caplog.text
    with open(path, "rb") as f:
        assert f.read() == b"sentinel"

    # a new cracked word changes the signature: full rewrite
    core.add_hashlines([_single_hit_line(2)])
    keygen_precompute(core)
    with open(path, "rb") as f:
        words = gzip.decompress(f.read())
    assert words == b"precracknet011\nprecracknet021\n"
