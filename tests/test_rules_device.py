"""On-device rule mangling vs the host interpreter (the executable spec).

The device path (rules/device.py) must reproduce rules/engine.py
bit-for-bit for every supported op — same position conventions, same
out-of-range no-ops, same reject semantics — with rejected/out-of-range
outputs surfacing as zeroed (None) columns instead of stream compaction.
"""

import numpy as np
import pytest

from dwpa_tpu import testing as T
from dwpa_tpu.models.m22000 import M22000Engine, MAX_PSK_LEN, MIN_PSK_LEN
from dwpa_tpu.rules import apply_rules, parse_rule, parse_rules
from dwpa_tpu.rules.device import (
    W,
    apply_rule_device,
    device_supported,
    encode_rule,
    simulate_lens,
    step_bucket,
)

# Varied shapes: empty, short, exactly-min, mixed case, digits, specials,
# 4-byte-boundary lengths, near-max, max.
WORDS = [
    b"",
    b"a",
    b"sevench",
    b"password",
    b"Password1",
    b"PASSWORD!",
    b"mIxEd CaSe words",
    b"0123456789abcdef",
    b"with.dots.and-dashes_",
    b"x" * 31,
    b"Y" * 32,
    b"wrap-around-word-here33",
    b"a b a b a b",
    b"zzzz" * 15 + b"zz",  # 62
    b"q" * 63,
]

# Every device op family, with in-range and out-of-range positions.
RULES = [
    ":", "l", "u", "c", "C", "t", "T0", "T3", "TZ", "r", "d", "f",
    "{", "}", "[", "]", "D0", "D5", "DZ", "x04", "x2A", "O12", "O9Z",
    "i3!", "iZ^", "o0#", "o8$", "'5", "'0", "$1", "$ ", "^0", "^~",
    "sab", "s  ", "saA", "z2", "Z3", "zA", "q", "k", "K", "*05", "*AZ",
    "L2", "R2", "+0", "-0", ".3", ",3", "y3", "Y3", "yZ", "e-", "E",
    "p2", "p0",
    "<5", "<Z", ">5", "_8", "!a", "/a", "(p", ")d", "=0p", "=5s", "%2a",
    # multi-step compositions, including grow-then-shrink
    "c $1 $2 $3", "u r ]", "T0 T1 T2 T3", "$1 $2 ] ]", "d '9", "l s0O u",
    "^a ^b ^c r", "f 'C", "z3 ]", "e- T0", "<Z $!",
]


def _host_expected(rule, word):
    out = rule.apply(word)
    if out is None or not MIN_PSK_LEN <= len(out) <= MAX_PSK_LEN:
        return None
    return out


@pytest.mark.parametrize("rtext", RULES)
def test_device_matches_host_interpreter(rtext):
    rule = parse_rule(rtext)
    assert device_supported(rule)
    got = apply_rule_device(WORDS, rule)
    for w, g in zip(WORDS, got):
        exp = _host_expected(rule, w)
        # device may defer an overflowing column to the host (None with
        # hostneed) — apply_rule_device already reports those as None,
        # and simulate_lens must have flagged them
        if g is None and exp is not None:
            _, hostneed = simulate_lens(rule, np.asarray([len(w)]))
            assert hostneed[0], f"{rtext!r} on {w!r}: expected {exp!r}, got None"
        else:
            assert g == exp, f"{rtext!r} on {w!r}: expected {exp!r}, got {g!r}"


def test_purge_not_device_supported():
    assert not device_supported(parse_rule("@a"))
    assert device_supported(parse_rule("sab $1"))


def test_encode_and_bucket():
    r = parse_rule("c $1 $2")
    enc = encode_rule(r)
    assert enc.shape == (3, 3) and enc.dtype == np.int32
    assert step_bucket(3) == 4 and step_bucket(4) == 4 and step_bucket(5) == 8


def test_simulate_lens_flags_overflow():
    rule = parse_rule("d d")  # 4x growth
    lens = np.asarray([10, W // 4, W // 2, W])
    out, hostneed = simulate_lens(rule, lens)
    assert list(hostneed) == [40 > W, False, True, True]
    assert out[0] == 40 and out[1] == W


def test_crack_rules_equals_host_expansion():
    """Engine-level: crack_rules finds exactly what host-expanded crack
    finds, planted PSK reachable only through a device rule."""
    rules = parse_rules([":", "u", "c $1", "$9 $9", "r"])
    base = [b"unit%04dword" % i for i in range(300)]
    # planted: "Unitword0217x" ... use rule "c $1" on base word
    psk = parse_rule("c $1").apply(b"unit0217word")
    assert psk == b"Unit0217word1"
    lines = [T.make_pmkid_line(psk, b"rules-dev-essid", seed="rd")]
    founds = M22000Engine(lines, batch_size=128).crack_rules(base, rules)
    assert len(founds) == 1 and founds[0].psk == psk
    founds2 = M22000Engine(lines, batch_size=128).crack(
        apply_rules(rules, base))
    assert len(founds2) == 1 and founds2[0].psk == psk


def test_crack_rules_host_fallbacks():
    """Unsupported ops (@), $HEX bases, and overflow pairs all route to
    host expansion and still crack."""
    # 1. '@' rule: only reachable by purging 'x'
    psk1 = b"abcdefgh1"
    rules = parse_rules(["@x"])
    lines = [T.make_pmkid_line(psk1, b"fb-essid-1", seed="f1")]
    founds = M22000Engine(lines, batch_size=64).crack_rules(
        [b"xaxbxcxdxexfxgxhx1"], rules)
    assert [f.psk for f in founds] == [psk1]

    # 2. $HEX base words bypass the device (rule semantics apply to the
    #    raw text, then the engine unhexes — matching the host path):
    #    ':' keeps the wrapper intact -> decoded PSK; '$!' breaks the
    #    wrapper -> literal candidate.  Both must equal host expansion.
    hexw = b"$HEX[" + b"hexbase9".hex().encode() + b"]"
    for rtext, psk2 in ((":", b"hexbase9"), ("$!", hexw + b"!")):
        rules2 = parse_rules([rtext])
        lines = [T.make_pmkid_line(psk2, b"fb-essid-2" + rtext.encode(),
                                   seed="f2" + rtext)]
        founds = M22000Engine(lines, batch_size=64).crack_rules([hexw], rules2)
        host = M22000Engine(lines, batch_size=64).crack(
            apply_rules(rules2, [hexw]))
        assert [f.psk for f in founds] == [psk2]
        assert [f.psk for f in host] == [psk2]

    # 3. overflow pair: 'd' doubles a 50-char word past W? (needs > W/2)
    base3 = b"m" * (W // 2 + 1)
    rule3 = parse_rules(["d 'C"])  # 102 bytes intermediate, truncate to 12
    psk3 = rule3[0].apply(base3)
    assert psk3 == b"m" * 12
    lines = [T.make_pmkid_line(psk3, b"fb-essid-3", seed="f3")]
    founds = M22000Engine(lines, batch_size=64).crack_rules([base3], rule3)
    assert [f.psk for f in founds] == [psk3]


def test_crack_rules_partial_batch_hit():
    """Regression (VERDICT r4 Weak #1): a hit in a PARTIAL device batch
    (nvalid < batch_size) must decode.  crack_rules pads the dispatch to
    cap = max(batch_size, ceil(nvalid/n)*n) but the decode once
    re-derived the per-shard width from nvalid alone, so hits in partial
    batches were sliced off or mapped to the wrong base word (then
    silently dropped by the oracle re-check).  Exact recorded repro:
    20 words, batch_size=64, rule ':', PSK = word 10, 8-device mesh."""
    base = [b"partial%03dw" % i for i in range(20)]
    psk = base[10]
    lines = [T.make_pmkid_line(psk, b"pb-essid", seed="pb")]
    founds = M22000Engine(lines, batch_size=64).crack_rules(
        base, parse_rules([":"]))
    assert [f.psk for f in founds] == [psk]


def test_crack_rules_partial_batch_hit_sliced_column():
    """Partial batch, hit at a local column >= the buggy per-shard width
    (ceil(nvalid/n)): with 20 valid words on an 8-way mesh the bad width
    was 3, so word 12 (shard 1, local col 4) was sliced off entirely."""
    base = [b"sliced%03dww" % i for i in range(20)]
    psk = parse_rule("u").apply(base[12])
    lines = [T.make_pmkid_line(psk, b"pb2-essid", seed="pb2")]
    founds = M22000Engine(lines, batch_size=64).crack_rules(
        base, parse_rules(["u"]))
    assert [f.psk for f in founds] == [psk]


def test_crack_rules_partial_final_batch_hit():
    """(a) multi-batch dict whose FINAL batch is partial and holds the
    hit — the shape every real dictionary ends with."""
    base = [b"finalb%04dw" % i for i in range(150)]  # batches: 128 + 22
    psk = parse_rule("$9").apply(base[141])
    lines = [T.make_pmkid_line(psk, b"fbp-essid", seed="fbp")]
    founds = M22000Engine(lines, batch_size=128).crack_rules(
        base, parse_rules(["$9"]))
    assert [f.psk for f in founds] == [psk]


def test_crack_rules_hex_shrunk_batch_hit():
    """(b) a full 64-word batch where $HEX bases route to the host
    fallback, shrinking the device batch's nvalid below batch_size; the
    hit lives in the shrunken plain set at a column the buggy width
    (ceil(14/8)=2) would slice (word 13 = shard 1, local col 5)."""
    hexes = [b"$HEX[" + (b"hx%04d" % i).hex().encode() + b"]"
             for i in range(50)]
    plain = [b"plainw%03dq" % i for i in range(14)]
    base = hexes + plain  # one flush() batch of 64
    psk = parse_rule("c").apply(plain[13])
    lines = [T.make_pmkid_line(psk, b"hxs-essid", seed="hxs")]
    founds = M22000Engine(lines, batch_size=64).crack_rules(
        base, parse_rules(["c"]))
    assert [f.psk for f in founds] == [psk]


def test_crack_rules_last_occupied_shard_hit():
    """(c) hit in the LAST shard holding valid words: nvalid=56 on an
    8-way mesh puts word 55 at shard 6's final local column; the buggy
    width (ceil(56/8)=7 vs true 8) dropped exactly that column."""
    base = [b"lastsh%03dww" % i for i in range(56)]
    psk = base[55]
    lines = [T.make_pmkid_line(psk, b"lsh-essid", seed="lsh")]
    founds = M22000Engine(lines, batch_size=64).crack_rules(
        base, parse_rules([":"]))
    assert [f.psk for f in founds] == [psk]


def test_crack_rules_skip_resume_contract():
    """skip=N fast-forwards the deterministic stream by exactly N
    candidates: wholly-covered sub-batches are not dispatched, a
    straddling sub-batch re-dispatches in full but reports only its
    remainder (at-least-once), and a find past the window still decodes.
    Covers device chunks AND the host-expanded tail ('@' rule)."""
    rules = parse_rules([":", "u", "c $1", "r", "@a"])  # 4 device + 1 host
    base = [b"skipw%04d" % i for i in range(150)]  # base batches: 128 + 22
    psk = parse_rule("c $1").apply(base[140])  # find lives in batch 2's chunk
    lines = [T.make_pmkid_line(psk, b"skip-essid", seed="sk")]

    def run(skip):
        seen = []
        founds = M22000Engine(lines, batch_size=128).crack_rules(
            base, rules, on_batch=lambda n, f: seen.append(n), skip=skip)
        return seen, founds

    # Full stream: batch1 chunk (128*4), batch1 tail (128), batch2 chunk
    # (22*4), batch2 tail (22) = 750 candidates.
    seen0, founds0 = run(0)
    assert seen0 == [512, 128, 88, 22]
    assert [f.psk for f in founds0] == [psk]
    total = sum(seen0)

    # Window ends exactly at a sub-batch boundary: batch1 chunk dropped.
    seen1, founds1 = run(512)
    assert seen1 == [128, 88, 22] and [f.psk for f in founds1] == [psk]
    # Window straddles the host tail: re-dispatched, remainder reported.
    seen2, founds2 = run(512 + 60)
    assert seen2 == [68, 88, 22] and [f.psk for f in founds2] == [psk]
    # Window covers everything: nothing dispatched, nothing found.
    seen3, founds3 = run(total)
    assert seen3 == [] and founds3 == []
    # Window straddles the find's own chunk: at-least-once replays it and
    # the find is still reported alongside the remainder count.
    seen4, founds4 = run(512 + 128 + 10)
    assert seen4 == [78, 22] and [f.psk for f in founds4] == [psk]
    # Invariant: reported + skipped == total, for every window.
    for skip, seen in ((512, seen1), (572, seen2), (total, seen3),
                       (650, seen4)):
        assert sum(seen) == total - skip


def test_crack_rules_on_batch_order():
    """on_batch fires in stream order with consumed counts covering the
    whole expanded stream (resume contract)."""
    rules = parse_rules([":", "u"])
    base = [b"orderw%03d" % i for i in range(100)]
    lines = [T.make_pmkid_line(b"not-there-1", b"ob-essid", seed="ob")]
    seen = []
    M22000Engine(lines, batch_size=64).crack_rules(
        base, rules, on_batch=lambda n, f: seen.append(n))
    # 2 base batches (64 + 36), both rules fused into one chunk each
    assert seen == [64 * 2, 36 * 2]
